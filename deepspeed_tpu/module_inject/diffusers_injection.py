"""Generic (diffusers/stable-diffusion) injection — the TPU analog of
reference `module_inject/replace_module.py:88` (`generic_injection`), the
`module_inject/containers/{unet,vae,clip}.py` policies,
`ops/transformer/inference/diffusers_attention.py`
(`DeepSpeedDiffusersAttention`) and the `csrc/spatial` fused bias-add
kernels (`csrc/spatial/csrc/opt_bias_add.cu`).

The reference mutates live torch modules, swapping UNet/VAE/CLIP
attention blocks for fused-CUDA versions. This framework is declarative:
`generic_injection` takes a torch-format STATE DICT (diffusers
`to_q/to_k/to_v/to_out.0` or CLIP `q_proj/k_proj/v_proj/out_proj`
spellings), recognizes the attention layout by key set — the role of
the reference policy `match()` — and returns (module, variables) where
the module is `DSSpatialAttention`: non-causal multi-head attention over
spatial/text tokens with optional cross-attention context, running the
shared `ops/attention.py` core. The `csrc/spatial` bias-add fusions are
expressed as `opt_bias_add` — plain jnp that XLA fuses into the
surrounding matmuls, which is the whole kernel's job on TPU.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.attention import attention


def opt_bias_add(x: jnp.ndarray, bias: Optional[jnp.ndarray] = None,
                 other: Optional[jnp.ndarray] = None,
                 residual: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Reference `csrc/spatial/csrc/opt_bias_add.cu` family
    (`bias_add`, `bias_add_add`, `bias_add_bias_add`): elementwise adds
    XLA fuses into the producing matmul — kept as a named op for parity
    and call-site clarity, not performance."""
    out = x if bias is None else x + bias
    if other is not None:
        out = out + other
    if residual is not None:
        out = out + residual
    return out


class DSSpatialAttention(nn.Module):
    """Reference `DeepSpeedDiffusersAttention` (triangular_masking=False):
    multi-head attention over (B, T, C) tokens; `context` switches to
    cross-attention (UNet's attn2). Weights live as (C_in, C) kernels —
    the converter below transposes torch's (out, in)."""
    hidden_size: int
    num_heads: int
    qkv_bias: bool = False
    out_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, context=None):
        c, nh = self.hidden_size, self.num_heads
        hd = c // nh
        ctx_src = x if context is None else context
        q = nn.Dense(c, use_bias=self.qkv_bias, dtype=self.dtype,
                     name="q")(x)
        k = nn.Dense(c, use_bias=self.qkv_bias, dtype=self.dtype,
                     name="k")(ctx_src)
        v = nn.Dense(c, use_bias=self.qkv_bias, dtype=self.dtype,
                     name="v")(ctx_src)
        b, t = q.shape[:2]
        tk = k.shape[1]
        ctx = attention(q.reshape(b, t, nh, hd), k.reshape(b, tk, nh, hd),
                        v.reshape(b, tk, nh, hd), causal=False)
        out = nn.Dense(c, use_bias=self.out_bias, dtype=self.dtype,
                       name="out")(ctx.reshape(b, t, c))
        return out


_Q_SPELLINGS = (
    ("to_q.weight", "to_k.weight", "to_v.weight",
     "to_out.0.weight", "to_out.0.bias"),            # diffusers UNet/VAE
    ("q_proj.weight", "k_proj.weight", "v_proj.weight",
     "out_proj.weight", "out_proj.bias"),            # CLIP
    ("query.weight", "key.weight", "value.weight",
     "proj_attn.weight", "proj_attn.bias"),          # diffusers VAE mid-block
)


def match_attention(sd: Dict[str, np.ndarray], prefix: str = ""):
    """The policy `match()` role: recognize a supported attention layout
    at `prefix` and return its key tuple, else None."""
    for keys in _Q_SPELLINGS:
        if all(prefix + k in sd for k in keys[:4]):
            return keys
    return None


def generic_injection(sd: Dict[str, np.ndarray], num_heads: int,
                      prefix: str = "", dtype: Any = jnp.float32
                      ) -> Tuple[DSSpatialAttention, Dict[str, Any]]:
    """Build (module, variables) for the attention found at `prefix` in a
    torch-format state dict (reference `generic_injection` +
    `replace_attn`). `num_heads` is REQUIRED — it is not recoverable from
    the weights, and a wrong head count reshapes into silently wrong
    attention. Raises on unrecognized layouts and on partial qkv biases —
    a silent passthrough would serve the unoptimized module without
    notice."""
    keys = match_attention(sd, prefix)
    if keys is None:
        raise ValueError(
            f"no supported attention layout at prefix {prefix!r} "
            f"(looked for {[k[0] for k in _Q_SPELLINGS]})")
    qk, kk, vk, ok, obk = keys
    qw = np.asarray(sd[prefix + qk])
    hidden = qw.shape[0]
    if hidden % num_heads:
        raise ValueError(
            f"hidden {hidden} not divisible by num_heads {num_heads}")
    params = {
        "q": {"kernel": qw.T},
        "k": {"kernel": np.asarray(sd[prefix + kk]).T},
        "v": {"kernel": np.asarray(sd[prefix + vk]).T},
        "out": {"kernel": np.asarray(sd[prefix + ok]).T},
    }
    bias_keys = [prefix + wk.replace("weight", "bias")
                 for wk in (qk, kk, vk)]
    have = [bk in sd for bk in bias_keys]
    if any(have) and not all(have):
        raise ValueError(
            f"partial qkv biases at prefix {prefix!r}: "
            f"{[bk for bk, h in zip(bias_keys, have) if h]} present, "
            f"{[bk for bk, h in zip(bias_keys, have) if not h]} missing")
    if all(have):
        for name, bk in zip(("q", "k", "v"), bias_keys):
            params[name]["bias"] = np.asarray(sd[bk])
    out_bias = prefix + obk in sd
    if out_bias:
        params["out"]["bias"] = np.asarray(sd[prefix + obk])
    module = DSSpatialAttention(
        hidden_size=hidden, num_heads=num_heads, qkv_bias=all(have),
        out_bias=out_bias, dtype=dtype)
    return module, {"params": params}
