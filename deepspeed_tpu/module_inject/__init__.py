"""HF model import (reference `deepspeed/module_inject/`).

The reference rewrites live torch modules (`replace_module.py:183`) and
slices their weights per TP rank (`auto_tp.py:_replace:330`). The TPU analog
is a *checkpoint converter*: HF safetensors/torch state dicts are mapped onto
the zoo's flax param trees (transposed to (in, out) kernels, per-layer
tensors stacked along the `nn.scan` layer axis) and placed directly into the
current mesh's shardings — the slicing is declarative, XLA moves the bytes.
"""

from deepspeed_tpu.module_inject.load_checkpoint import (  # noqa: F401
    from_hf_config, load_hf_checkpoint, load_state_dict)
from deepspeed_tpu.module_inject.diffusers_injection import (  # noqa: F401
    DSSpatialAttention, generic_injection, opt_bias_add)
