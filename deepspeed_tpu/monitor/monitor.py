"""Metric event sinks.

Counterpart of reference `deepspeed/monitor/monitor.py:30` (`MonitorMaster`
dispatching to TensorBoard/WandB/Comet/CSV). Events are `(tag, value, step)`
tuples; only process 0 writes.
"""

from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger


class Monitor:
    def __init__(self, config):
        self.enabled = config.enabled

    def write_events(self, event_list: List[Tuple]):
        raise NotImplementedError


class CsvMonitor(Monitor):
    """Reference: monitor/csv_monitor.py."""

    def __init__(self, config):
        super().__init__(config)
        self.output_path = config.output_path or "./csv_monitor"
        self.job_name = config.job_name
        self._files = {}
        if self.enabled:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def _file(self, tag: str):
        if tag not in self._files:
            safe = tag.replace("/", "_")
            path = os.path.join(self.output_path, self.job_name, f"{safe}.csv")
            f = open(path, "a", newline="")
            self._files[tag] = (f, csv.writer(f))
        return self._files[tag]

    def write_events(self, event_list):
        if not self.enabled:
            return
        for tag, value, step in event_list:
            f, writer = self._file(tag)
            writer.writerow([step, value])
            f.flush()


class JsonlMonitor(Monitor):
    """Structured JSONL sink — the telemetry hub's line format applied to
    monitor events: one line per event, ``{"ts", "tag", "value", "step"}``
    (field names are schema — docs/telemetry.md). Appends, like CsvMonitor,
    so resumed jobs extend the same file."""

    def __init__(self, config):
        super().__init__(config)
        self.output_path = config.output_path or "./jsonl_monitor"
        self.job_name = config.job_name
        self._f = None
        if self.enabled:
            d = os.path.join(self.output_path, self.job_name)
            os.makedirs(d, exist_ok=True)
            self.log_path = os.path.join(d, "events.jsonl")

    def write_events(self, event_list):
        if not self.enabled:
            return
        import json
        import time
        if self._f is None:
            self._f = open(self.log_path, "a")
        for tag, value, step in event_list:
            self._f.write(json.dumps({"ts": round(time.time(), 6),
                                      "tag": tag, "value": float(value),
                                      "step": int(step)}) + "\n")
        self._f.flush()


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter
                path = os.path.join(config.output_path or "./tb_logs", config.job_name)
                self.summary_writer = SummaryWriter(log_dir=path)
            except Exception as e:
                logger.warning(f"tensorboard unavailable ({e}); disabling sink")
                self.enabled = False

    def write_events(self, event_list):
        if not self.enabled or self.summary_writer is None:
            return
        for tag, value, step in event_list:
            self.summary_writer.add_scalar(tag, value, step)
        self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self._wandb = None
        if self.enabled:
            try:
                import wandb
                wandb.init(project=config.project, group=config.group, entity=config.team)
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"wandb unavailable ({e}); disabling sink")
                self.enabled = False

    def write_events(self, event_list):
        if not self.enabled or self._wandb is None:
            return
        for tag, value, step in event_list:
            self._wandb.log({tag: value}, step=step)


class CometMonitor(Monitor):
    """Reference monitor/comet.py — comet_ml sink (soft dependency)."""

    def __init__(self, cfg):
        self.enabled = bool(getattr(cfg, "enabled", False))
        self._exp = None
        if self.enabled:
            try:
                import comet_ml
                self._exp = comet_ml.Experiment(
                    project_name=getattr(cfg, "project", None) or None)
            except Exception as e:
                logger.warning(f"comet_ml unavailable ({e}); disabling sink")
                self.enabled = False

    def write_events(self, events):
        if not self.enabled or self._exp is None:
            return
        for name, value, step in events:
            self._exp.log_metric(name, value, step=step)


class MonitorMaster(Monitor):
    """Fan-out to all configured sinks; rank-0 only (reference monitor.py:30)."""

    def __init__(self, ds_config):
        import jax
        self._rank0 = jax.process_index() == 0
        self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard) if self._rank0 else None
        self.csv_monitor = CsvMonitor(ds_config.csv_monitor) if self._rank0 else None
        self.wandb_monitor = WandbMonitor(ds_config.wandb) if self._rank0 else None
        comet_cfg = getattr(ds_config, "comet", None)
        self.comet_monitor = CometMonitor(comet_cfg) \
            if (self._rank0 and comet_cfg is not None) else None
        jsonl_cfg = getattr(ds_config, "jsonl_monitor", None)
        self.jsonl_monitor = JsonlMonitor(jsonl_cfg) \
            if (self._rank0 and jsonl_cfg is not None) else None
        self.enabled = self._rank0 and any(
            m is not None and m.enabled
            for m in (self.tb_monitor, self.csv_monitor, self.wandb_monitor,
                      self.comet_monitor, self.jsonl_monitor))

    def write_events(self, event_list):
        if not self._rank0:
            return
        for m in (self.tb_monitor, self.csv_monitor, self.wandb_monitor,
                  self.comet_monitor, self.jsonl_monitor):
            if m is not None and m.enabled:
                m.write_events(event_list)
