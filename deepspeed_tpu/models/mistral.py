"""Mistral model family.

Reference slot: `inference/v2/model_implementations/mistral` and the
`module_inject` llama-policy path (HF Mistral shares llama's layer schema).
Mistral is the llama decoder with sliding-window attention — the family
reuses `LlamaForCausalLM` with `sliding_window` set, which bands the causal
mask in both the training attention (reference/blockwise XLA paths) and the
KV-cache decode mask. Checkpoints that disable the window (v0.2+,
sliding_window=null) degenerate to exact llama behavior.
"""

from __future__ import annotations

from deepspeed_tpu.models.llama import (
    LlamaConfig, LlamaForCausalLM, init_params_and_specs, llama_loss_fn,
    llama_pipeline_fns, materialize_params)

MistralConfig = LlamaConfig
MistralForCausalLM = LlamaForCausalLM

PRESETS = {
    "mistral-7b": dict(vocab_size=32000, hidden_size=4096,
                       intermediate_size=14336, num_hidden_layers=32,
                       num_attention_heads=32, num_key_value_heads=8,
                       max_position_embeddings=32768, rope_theta=10000.0,
                       rms_norm_eps=1e-5, sliding_window=4096),
    "mistral-tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=2, max_position_embeddings=128,
                         sliding_window=16, remat=False),
}


def mistral_config(name: str, **overrides) -> MistralConfig:
    return MistralConfig(**{**PRESETS[name], **overrides})


__all__ = ["MistralConfig", "MistralForCausalLM", "mistral_config", "PRESETS",
           "init_params_and_specs", "materialize_params",
           "llama_pipeline_fns", "llama_loss_fn"]
