"""GPT-J model family (EleutherAI GPT-J-6B lineage).

Reference slot: `module_inject/containers/gptj.py` (DS_GPTJContainer,
HFGPTJLayerPolicy). The GPT-J block is a distinct architecture in the zoo:
ONE LayerNorm feeds BOTH the attention and the MLP, whose outputs add onto
the residual in PARALLEL (`h + attn(ln(h)) + mlp(ln(h))`), rotary is
partial (`rotary_dim`, 64 of 256 on 6B) and INTERLEAVED (rotate-every-two,
unlike the half-split NeoX/llama layout), attention projections carry no
bias while the MLP and the lm_head do.

Same TPU mapping as the rest of the zoo: nn.scan block stack with logical
axis names, shared-params KV-cache path, HF import via
`module_inject/load_checkpoint.py`.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import (
    causal_lm_loss, dense as _dense, layer_norm as _ln,
    make_causal_loss_fn)
from deepspeed_tpu.ops.attention import attention, cached_attention
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


@dataclasses.dataclass(frozen=True)
class GPTJConfig:
    vocab_size: int = 50400
    hidden_size: int = 4096
    intermediate_size: int = 16384
    num_hidden_layers: int = 28
    num_attention_heads: int = 16
    max_position_embeddings: int = 2048
    rotary_dim: int = 64
    layer_norm_eps: float = 1e-5
    remat: bool = True
    remat_policy: str = "nothing"
    attn_impl: str = "auto"
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


PRESETS = {
    "gptj-6b": dict(),
    "gptj-tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=128, rotary_dim=8,
                      remat=False),
}


def gptj_config(name: str, **overrides) -> GPTJConfig:
    return GPTJConfig(**{**PRESETS[name], **overrides})


def _interleaved_rope(x, positions, rotary_dim: int, theta: float = 10000.0):
    """GPT-J rotary: rotate-every-two over the FIRST `rotary_dim` channels
    (HF GPTJAttention.apply_rotary_pos_emb — sin/cos repeat per PAIR, the
    pair being adjacent channels, not split halves)."""
    d2 = rotary_dim // 2
    inv = 1.0 / (theta ** (jnp.arange(0, d2, dtype=jnp.float32) * 2 / rotary_dim))
    ang = positions.astype(jnp.float32)[..., None] * inv      # (..., S, d2)
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    x1 = rot[..., 0::2].astype(jnp.float32)                   # (B,S,H,d2)
    x2 = rot[..., 1::2].astype(jnp.float32)
    if cos.ndim == 2:                                         # (S, d2)
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    else:                                                     # (B, S, d2)
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    o1 = x1 * cos - x2 * sin
    o2 = x1 * sin + x2 * cos
    rot = jnp.stack([o1, o2], axis=-1).reshape(rot.shape).astype(x.dtype)
    return jnp.concatenate([rot, rest], axis=-1)


class GPTJAttention(nn.Module):
    cfg: GPTJConfig

    @nn.compact
    def __call__(self, h, positions, kv=None, mask=None, index=None):
        cfg = self.cfg
        hd, nh = cfg.head_dim, cfg.num_attention_heads
        q = _dense(nh * hd, ("embed", "heads"), cfg.dtype, "q_proj")(h)
        k = _dense(nh * hd, ("embed", "kv_heads"), cfg.dtype, "k_proj")(h)
        v = _dense(nh * hd, ("embed", "kv_heads"), cfg.dtype, "v_proj")(h)
        b, s = h.shape[:2]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nh, hd)
        v = v.reshape(b, s, nh, hd)
        q = _interleaved_rope(q, positions, cfg.rotary_dim)
        k = _interleaved_rope(k, positions, cfg.rotary_dim)

        if kv is not None:
            from deepspeed_tpu.inference.kv_cache import update_layer
            k_cache, v_cache = update_layer(kv[0], kv[1], k, v, index)
            ctx = cached_attention(q, k_cache, v_cache, index, mask,
                                   impl=cfg.attn_impl)
            out = _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                         "out_proj")(ctx.reshape(b, s, nh * hd))
            return out, (k_cache, v_cache)

        ctx = attention(q, k, v, causal=True, impl=cfg.attn_impl)
        return _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                      "out_proj")(ctx.reshape(b, s, nh * hd))


class GPTJMLP(nn.Module):
    cfg: GPTJConfig

    @nn.compact
    def __call__(self, h):
        cfg = self.cfg
        up = _dense(cfg.intermediate_size, ("embed", "mlp"), cfg.dtype,
                    "fc_in", use_bias=True)(h)
        # HF GPT-J activation_function="gelu_new" (tanh gelu)
        return _dense(cfg.hidden_size, ("mlp_in", "embed"), cfg.dtype,
                      "fc_out", use_bias=True)(nn.gelu(up, approximate=True))


class GPTJBlock(nn.Module):
    cfg: GPTJConfig

    @nn.compact
    def __call__(self, h, aux, kv=None):
        cfg = self.cfg
        ln = _ln(cfg.layer_norm_eps, cfg.dtype, "ln_1")
        if kv is not None:
            positions, index, mask = aux
            normed = ln(h)
            attn, new_kv = GPTJAttention(cfg, name="attn")(
                normed, positions, kv=kv, mask=mask, index=index)
            h = h + attn + GPTJMLP(cfg, name="mlp")(normed)
            return h, new_kv
        positions = aux
        h = shard_along(h, BATCH_AXES, "sequence", None)
        normed = ln(h)
        # parallel residual off ONE norm — the block shape kernel injection
        # fuses in the reference (containers/gptj.py)
        h = h + GPTJAttention(cfg, name="attn")(normed, positions) \
            + GPTJMLP(cfg, name="mlp")(normed)
        return h, None


class GPTJForCausalLM(nn.Module):
    cfg: GPTJConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, positions=None, cache=None):
        cfg = self.cfg
        embed = self.param("wte", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0)
        h = shard_along(h, BATCH_AXES, "sequence", None)

        if cache is not None:
            from deepspeed_tpu.inference.kv_cache import decode_mask
            b, s = input_ids.shape
            index = cache.index
            positions = index[:, None] + jnp.arange(s)[None, :]
            mask = decode_mask(positions, cache.max_len)
            ScanBlocks = nn.scan(
                GPTJBlock, variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="h")(
                h, (positions, index, mask), (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = _ln(cfg.layer_norm_eps, cfg.dtype, "ln_f")(h)
            return self._lm_head(h), new_cache

        if positions is None:
            positions = jnp.arange(input_ids.shape[1])
        block = GPTJBlock
        if cfg.remat:
            from deepspeed_tpu.models.llama import _remat_policy
            block = nn.remat(block, prevent_cse=False,
                             policy=_remat_policy(cfg.remat_policy))
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0}, split_rngs={"params": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="h")(h, positions)
        h = _ln(cfg.layer_norm_eps, cfg.dtype, "ln_f")(h)
        logits = self._lm_head(h)
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}

    def _lm_head(self, h):
        cfg = self.cfg
        w = self.param("lm_head", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("embed", "vocab")),
            (cfg.hidden_size, cfg.vocab_size), jnp.float32)
        b = self.param("lm_head_bias", nn.with_logical_partitioning(
            nn.initializers.zeros, ("vocab",)),
            (cfg.vocab_size,), jnp.float32)
        return h @ w.astype(cfg.dtype) + b.astype(cfg.dtype)


def init_gptj(cfg: GPTJConfig, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = GPTJForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)

    def init_fn(rng):
        variables = model.init(rng, ids)
        raw, _ = extract_params_and_specs(variables)
        return raw

    params = jax.jit(init_fn)(rng)
    variables = jax.eval_shape(model.init, rng, ids)
    _, specs = extract_params_and_specs(variables)
    return model, params, specs


def gptj_loss_fn(model):
    return make_causal_loss_fn(model)



def gptj_pipeline_fns(model: GPTJForCausalLM):
    """Functional pipeline pieces (see models/llama.py:llama_pipeline_fns)."""
    from deepspeed_tpu.models.common import apply_ln, make_chunk_fn
    cfg = model.cfg

    def embed_fn(params, ids):
        return jnp.take(params["wte"].astype(cfg.dtype), ids, axis=0)

    def aux_fn(params, ids):
        return jnp.arange(ids.shape[-1])

    def head_fn(params, h, ids, labels):
        h = apply_ln(params["ln_f"], h, cfg.layer_norm_eps, cfg.dtype)
        logits = h @ params["lm_head"].astype(cfg.dtype) \
            + params["lm_head_bias"].astype(cfg.dtype)
        return causal_lm_loss(logits, ids, labels)

    return embed_fn, aux_fn, make_chunk_fn(GPTJBlock, cfg), head_fn, "h"
