"""GPT-2 model family (BASELINE config 1: ZeRO-1 GPT-2 125M).

Counterpart of the reference's GPT-2 support (`module_inject/containers/
gpt2.py`, megatron fixtures in tests): learned positions, pre-LN blocks,
GELU MLP, tied embeddings. Same logical-partitioning scheme as llama.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import causal_lm_loss, shift_labels
from deepspeed_tpu.ops.attention import attention
from deepspeed_tpu.sequence.layer import DistributedAttention
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    embd_pdrop: float = 0.0
    attn_pdrop: float = 0.0
    remat: bool = False
    attn_impl: str = "auto"
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


PRESETS = {
    "gpt2-125m": dict(vocab_size=50257, hidden_size=768, num_hidden_layers=12,
                      num_attention_heads=12, intermediate_size=3072),
    "gpt2-medium": dict(vocab_size=50257, hidden_size=1024, num_hidden_layers=24,
                        num_attention_heads=16, intermediate_size=4096),
    "gpt2-tiny": dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=128,
                      max_position_embeddings=128),
}


def gpt2_config(name: str, **overrides) -> GPT2Config:
    return GPT2Config(**{**PRESETS[name], **overrides})


def _dense(features, logical, cfg, name, bias=True):
    return nn.Dense(features, use_bias=bias, dtype=cfg.dtype, param_dtype=jnp.float32,
                    kernel_init=nn.with_logical_partitioning(
                        nn.initializers.normal(0.02), logical),
                    name=name)


class GPT2Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, h, aux=None, kv=None):
        cfg = self.cfg
        b, s, d = h.shape
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        if kv is None:
            h = shard_along(h, BATCH_AXES, "sequence", None)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name="ln_1")(h)
        qkv = _dense(3 * d, ("embed", "heads"), cfg, "c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nh, hd)
        v = v.reshape(b, s, nh, hd)

        if kv is not None:
            from deepspeed_tpu.inference.kv_cache import update_layer
            from deepspeed_tpu.ops.attention import cached_attention
            index, mask = aux
            k_cache, v_cache = update_layer(kv[0], kv[1], k, v, index)
            ctx = cached_attention(q, k_cache, v_cache, index, mask,
                                   impl=cfg.attn_impl)
            new_kv = (k_cache, v_cache)
        else:
            def core(q, k, v):
                return attention(q, k, v, causal=True, impl=cfg.attn_impl)

            ctx = DistributedAttention(core)(q, k, v)
            new_kv = None
        h = h + _dense(d, ("heads_in", "embed"), cfg, "c_proj")(ctx.reshape(b, s, d))
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name="ln_2")(h)
        x = _dense(cfg.intermediate_size, ("embed", "mlp"), cfg, "c_fc")(x)
        x = nn.gelu(x, approximate=True)
        h = h + _dense(d, ("mlp_in", "embed"), cfg, "mlp_proj")(x)
        return h, new_kv


class GPT2LMHeadModel(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, input_ids, labels=None, cache=None):
        cfg = self.cfg
        wte = self.param("wte", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        wpe = self.param("wpe", nn.with_logical_partitioning(
            nn.initializers.normal(0.01), (None, "embed")),
            (cfg.max_position_embeddings, cfg.hidden_size), jnp.float32)
        s = input_ids.shape[1]

        if cache is not None:
            from deepspeed_tpu.inference.kv_cache import decode_mask
            index = cache.index
            positions = index[:, None] + jnp.arange(s)[None, :]
            h = jnp.take(wte.astype(cfg.dtype), input_ids, axis=0) + \
                jnp.take(wpe.astype(cfg.dtype), positions, axis=0)
            mask = decode_mask(positions, cache.max_len)
            ScanBlocks = nn.scan(
                GPT2Block, variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="h")(
                h, (index, mask), (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                             name="ln_f")(h)
            logits = jnp.einsum("bsd,vd->bsv", h, wte.astype(cfg.dtype))
            return logits, new_cache

        h = jnp.take(wte.astype(cfg.dtype), input_ids, axis=0) + \
            wpe[None, :s].astype(cfg.dtype)
        h = shard_along(h, BATCH_AXES, "sequence", None)

        block = GPT2Block
        if cfg.remat:
            block = nn.remat(block, prevent_cse=False,
                             policy=jax.checkpoint_policies.nothing_saveable)
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0}, split_rngs={"params": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="h")(h, None)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name="ln_f")(h)
        logits = jnp.einsum("bsd,vd->bsv", h, wte.astype(cfg.dtype))
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}


def gpt2_pipeline_fns(model: GPT2LMHeadModel):
    """Functional pipeline pieces (see models/llama.py:llama_pipeline_fns)."""
    cfg = model.cfg

    def embed_fn(params, ids):
        s = ids.shape[1]
        return jnp.take(params["wte"].astype(cfg.dtype), ids, axis=0) + \
            params["wpe"][None, :s].astype(cfg.dtype)

    def aux_fn(params, ids):
        return None

    def chunk_fn(local_layers, x, aux):
        def body(h, layer_params):
            h, _ = GPT2Block(cfg).apply({"params": layer_params}, h, aux)
            return h, None
        if cfg.remat:
            body = jax.checkpoint(
                body, prevent_cse=False,
                policy=jax.checkpoint_policies.nothing_saveable)
        return jax.lax.scan(body, x, local_layers)[0]

    def head_fn(params, h, ids, labels):
        ln = params["ln_f"]
        mean = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.var(h, axis=-1, keepdims=True)
        h = (h - mean) * jax.lax.rsqrt(var + cfg.layer_norm_epsilon)
        h = (h * ln["scale"] + ln["bias"]).astype(cfg.dtype)
        logits = jnp.einsum("bsd,vd->bsv", h, params["wte"].astype(cfg.dtype))
        return causal_lm_loss(logits, ids, labels)

    return embed_fn, aux_fn, chunk_fn, head_fn, "h"


def init_gpt2(cfg: GPT2Config, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = GPT2LMHeadModel(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)
    variables = model.init(rng, ids)
    raw, specs = extract_params_and_specs(variables)
    return model, raw, specs


def gpt2_loss_fn(model: GPT2LMHeadModel):
    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = shift_labels(ids)
        return model.apply({"params": params}, ids, labels=labels)
    return loss_fn
