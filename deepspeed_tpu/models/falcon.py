"""Falcon model family (Falcon-7B-style decoder).

Reference slot: `inference/v2/model_implementations/falcon` +
`module_inject` policy coverage. The classic Falcon block is PARALLEL
(`parallel_attn`): one LayerNorm feeds both attention and MLP, outputs add
onto the residual together; attention is multi-query (one shared K/V head)
or grouped; projections carry no bias; rotary is full-dim NeoX-style.

Supported: `parallel_attn=True`, `new_decoder_architecture=False` (7B
lineage — the 40B+ per-group fused-QKV layout is rejected at import).
Same TPU design as the llama flagship: `nn.scan` stack, logical
partitioning, shared training/KV-cache parameterization.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import (
    causal_lm_loss, dense as _common_dense, layer_norm as _ln,
    make_causal_loss_fn)
from deepspeed_tpu.ops.attention import (
    apply_rotary_emb, attention, cached_attention, rope_cos_sin)
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


@dataclasses.dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    num_hidden_layers: int = 32
    num_attention_heads: int = 71
    num_kv_heads: int = 1               # multi_query=True → 1
    max_position_embeddings: int = 2048
    rope_theta: float = 10000.0
    layer_norm_epsilon: float = 1e-5
    remat: bool = True
    remat_policy: str = "nothing"
    attn_impl: str = "auto"
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size


PRESETS = {
    "falcon-7b": dict(vocab_size=65024, hidden_size=4544, num_hidden_layers=32,
                      num_attention_heads=71, num_kv_heads=1,
                      max_position_embeddings=2048),
    "falcon-tiny": dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                        num_attention_heads=4, num_kv_heads=1,
                        max_position_embeddings=128, remat=False),
}


def falcon_config(name: str, **overrides) -> FalconConfig:
    return FalconConfig(**{**PRESETS[name], **overrides})




class FalconAttention(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, h, cos, sin, kv=None, mask=None, index=None):
        cfg = self.cfg
        hd, nh, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.num_kv_heads
        q = _dense(nh * hd, ("embed", "heads"), cfg.dtype, "q_proj")(h)
        k = _dense(nkv * hd, ("embed", "kv_heads"), cfg.dtype, "k_proj")(h)
        v = _dense(nkv * hd, ("embed", "kv_heads"), cfg.dtype, "v_proj")(h)
        b, s = h.shape[:2]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nkv, hd)
        v = v.reshape(b, s, nkv, hd)
        q = apply_rotary_emb(q, cos, sin)
        k = apply_rotary_emb(k, cos, sin)

        if kv is not None:
            from deepspeed_tpu.inference.kv_cache import update_layer
            k_cache, v_cache = update_layer(kv[0], kv[1], k, v, index)
            ctx = cached_attention(q, k_cache, v_cache, index, mask,
                                   impl=cfg.attn_impl)
            out = _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                         "dense")(ctx.reshape(b, s, nh * hd))
            return out, (k_cache, v_cache)

        ctx = attention(q, k, v, causal=True, impl=cfg.attn_impl)
        return _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                      "dense")(ctx.reshape(b, s, nh * hd))


class FalconMLP(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, h):
        cfg = self.cfg
        up = _dense(cfg.intermediate_size, ("embed", "mlp"), cfg.dtype,
                    "dense_h_to_4h")(h)
        return _dense(cfg.hidden_size, ("mlp_in", "embed"), cfg.dtype,
                      "dense_4h_to_h")(nn.gelu(up, approximate=False))


class FalconBlock(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, h, cos_sin, kv=None):
        cfg = self.cfg
        if kv is not None:
            cos, sin, index, mask = cos_sin
            normed = _ln(cfg.layer_norm_epsilon, cfg.dtype, "input_layernorm")(h)
            attn, new_kv = FalconAttention(cfg, name="self_attention")(
                normed, cos, sin, kv=kv, mask=mask, index=index)
            h = h + attn + FalconMLP(cfg, name="mlp")(normed)
            return h, new_kv
        cos, sin = cos_sin
        h = shard_along(h, BATCH_AXES, "sequence", None)
        normed = _ln(cfg.layer_norm_epsilon, cfg.dtype, "input_layernorm")(h)
        h = h + FalconAttention(cfg, name="self_attention")(normed, cos, sin) \
            + FalconMLP(cfg, name="mlp")(normed)
        return h, None


class FalconForCausalLM(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, positions=None, cache=None):
        cfg = self.cfg
        embed = self.param("word_embeddings", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0)
        h = shard_along(h, BATCH_AXES, "sequence", None)

        if cache is not None:
            from deepspeed_tpu.inference.kv_cache import decode_mask
            b, s = input_ids.shape
            index = cache.index
            positions = index[:, None] + jnp.arange(s)[None, :]
            cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                                    cfg.dtype)
            mask = decode_mask(positions, cache.max_len)
            ScanBlocks = nn.scan(
                FalconBlock, variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="h")(
                h, (cos, sin, index, mask), (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = _ln(cfg.layer_norm_epsilon, cfg.dtype, "ln_f")(h)
            return self._lm_head(h, embed), new_cache

        if positions is None:
            positions = jnp.arange(input_ids.shape[1])
        cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta, cfg.dtype)
        block = FalconBlock
        if cfg.remat:
            from deepspeed_tpu.models.llama import _remat_policy
            block = nn.remat(block, prevent_cse=False,
                             policy=_remat_policy(cfg.remat_policy))
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0}, split_rngs={"params": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="h")(h, (cos, sin))
        h = _ln(cfg.layer_norm_epsilon, cfg.dtype, "ln_f")(h)
        logits = self._lm_head(h, embed)
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}

    def _lm_head(self, h, embed):
        # HF Falcon ties the LM head to the word embeddings
        return jnp.einsum("bsd,vd->bsv", h, embed.astype(self.cfg.dtype))


def init_falcon(cfg: FalconConfig, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = FalconForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)

    def init_fn(rng):
        variables = model.init(rng, ids)
        raw, _ = extract_params_and_specs(variables)
        return raw

    params = jax.jit(init_fn)(rng)
    variables = jax.eval_shape(model.init, rng, ids)
    _, specs = extract_params_and_specs(variables)
    return model, params, specs


def falcon_loss_fn(model):
    return make_causal_loss_fn(model)


def _dense(features, logical, dtype, name, use_bias: bool = False):
    return _common_dense(features, logical, dtype, name, use_bias=use_bias)


def falcon_pipeline_fns(model: FalconForCausalLM):
    """Functional pipeline pieces (see models/llama.py:llama_pipeline_fns)."""
    from deepspeed_tpu.models.common import apply_ln, make_chunk_fn
    cfg = model.cfg

    def embed_fn(params, ids):
        return jnp.take(params["word_embeddings"].astype(cfg.dtype), ids,
                        axis=0)

    def aux_fn(params, ids):
        return rope_cos_sin(jnp.arange(ids.shape[-1]), cfg.head_dim,
                            cfg.rope_theta, cfg.dtype)

    def head_fn(params, h, ids, labels):
        h = apply_ln(params["ln_f"], h, cfg.layer_norm_epsilon, cfg.dtype)
        logits = jnp.einsum("bsd,vd->bsv", h,
                            params["word_embeddings"].astype(cfg.dtype))
        return causal_lm_loss(logits, ids, labels)

    return embed_fn, aux_fn, make_chunk_fn(FalconBlock, cfg), head_fn, "h"
