from deepspeed_tpu.models.bert import (
    BertConfig, BertForMaskedLM, bert_config, bert_loss_fn, init_bert)
from deepspeed_tpu.models.bloom import (
    BloomConfig, BloomForCausalLM, bloom_config, bloom_loss_fn, init_bloom)
from deepspeed_tpu.models.falcon import (
    FalconConfig, FalconForCausalLM, falcon_config, falcon_loss_fn, init_falcon)
from deepspeed_tpu.models.gpt2 import (
    GPT2Config, GPT2LMHeadModel, gpt2_config, gpt2_loss_fn, init_gpt2)
from deepspeed_tpu.models.gptj import (
    GPTJConfig, GPTJForCausalLM, gptj_config, gptj_loss_fn, init_gptj)
from deepspeed_tpu.models.gptneo import (
    GPTNeoConfig, GPTNeoForCausalLM, gptneo_config, gptneo_loss_fn,
    init_gptneo)
from deepspeed_tpu.models.gptneox import (
    GPTNeoXConfig, GPTNeoXForCausalLM, gptneox_config, gptneox_loss_fn,
    init_gptneox)
from deepspeed_tpu.models.phi import (
    PhiConfig, PhiForCausalLM, init_phi, phi_config, phi_loss_fn)
from deepspeed_tpu.models.llama import (
    LlamaConfig, LlamaForCausalLM, init_params_and_specs, llama_config,
    llama_loss_fn, materialize_params)
from deepspeed_tpu.models.mistral import (
    MistralConfig, MistralForCausalLM, mistral_config)
from deepspeed_tpu.models.qwen2_moe import (
    Qwen2MoeConfig, Qwen2MoeForCausalLM, init_qwen2_moe, qwen2_moe_config,
    qwen2_moe_loss_fn)
from deepspeed_tpu.models.qwen2 import (
    Qwen2Config, Qwen2ForCausalLM, qwen2_config)
