"""Shared model-zoo pieces: losses, embedding helpers.

The loss here is the counterpart of the reference's sequence-parallel
vocab-parallel cross entropy (`deepspeed/sequence/cross_entropy.py`): with
logits sharded over the `model` (vocab) and/or `sequence` axes, the reductions
XLA emits from the shardings are the same ones the reference codes by hand.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray,
                       ignore_index: int = IGNORE_INDEX,
                       z_loss: float = 0.0) -> jnp.ndarray:
    """Mean token CE in fp32. logits (B, S, V), labels (B, S)."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    idx = jnp.clip(labels, 0, logits.shape[-1] - 1)
    picked = jnp.take_along_axis(logits, idx[..., None], axis=-1)[..., 0]
    token_loss = lse - picked
    if z_loss > 0.0:
        token_loss = token_loss + z_loss * jnp.square(lse)
    mask = (labels != ignore_index).astype(jnp.float32)
    return jnp.sum(token_loss * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def shift_labels(input_ids: jnp.ndarray, ignore_index: int = IGNORE_INDEX) -> jnp.ndarray:
    """Next-token labels: labels[t] = input_ids[t+1]; last position ignored."""
    return jnp.concatenate(
        [input_ids[:, 1:], jnp.full_like(input_ids[:, :1], ignore_index)], axis=1)


def causal_lm_loss(logits: jnp.ndarray, input_ids: jnp.ndarray,
                   labels: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    if labels is None:
        labels = shift_labels(input_ids)
    return cross_entropy_loss(logits, labels)


def dense(features, logical, dtype, name, use_bias: bool = False):
    """Zoo-standard projection: logical-axis-partitioned kernel (+ bias)."""
    import flax.linen as nn
    return nn.Dense(features, use_bias=use_bias, dtype=dtype,
                    param_dtype=jnp.float32,
                    kernel_init=nn.with_logical_partitioning(
                        nn.initializers.normal(0.02), logical),
                    bias_init=nn.with_logical_partitioning(
                        nn.initializers.zeros_init(), (logical[-1],)),
                    name=name)


def layer_norm(eps, dtype, name):
    """Zoo-standard LayerNorm (fp32 scale+bias, 'embed' logical axis)."""
    import flax.linen as nn
    return nn.LayerNorm(epsilon=eps, dtype=dtype, param_dtype=jnp.float32,
                        scale_init=nn.with_logical_partitioning(
                            nn.initializers.ones_init(), ("embed",)),
                        bias_init=nn.with_logical_partitioning(
                            nn.initializers.zeros_init(), ("embed",)),
                        name=name)


def collect_router_metrics(mut) -> dict:
    """Per-layer router telemetry out of a model apply's mutated 'metrics'
    collection: the MoE layers sow per-expert load and drop fractions
    (moe/layer.py), which nn.scan stacks to (L, E)/(L,) per model. Returned
    as plain aux-dict entries so the engine's MetricsState carries them to
    the host with the loss."""
    metrics = mut.get("metrics", {}) if hasattr(mut, "get") else {}
    out = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(metrics)
    for path, leaf in flat:
        keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
        if "router_load" in keys:
            out["router_load"] = leaf
        elif "router_drop" in keys:
            out["router_drop"] = leaf
    return out


def make_causal_loss_fn(model):
    """Standard engine loss_fn for a causal-LM zoo model: shift labels when
    the batch doesn't carry them."""
    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = shift_labels(ids)
        return model.apply({"params": params}, ids, labels=labels)
    return loss_fn


# ---------------------------------------------------------------- pipeline
def apply_ln(sub_params, h, eps, dtype):
    """Apply a flax LayerNorm given its param subtree — pipeline head/embed
    fns reuse the module math instead of hand-rolling it."""
    import flax.linen as nn
    return nn.LayerNorm(epsilon=eps, dtype=dtype,
                        param_dtype=jnp.float32).apply({"params": sub_params}, h)


def apply_rms(sub_params, h, eps, dtype):
    from deepspeed_tpu.models.llama import RMSNorm
    return RMSNorm(eps, dtype).apply({"params": sub_params}, h)


def make_chunk_fn(block_cls, cfg, moe_aux_coef=None):
    """Pipeline stage body shared by the zoo (see
    `models/llama.py:llama_pipeline_fns`): scan `block_cls` over the stage's
    local layer stack, rematting per block like the dp path. With
    `moe_aux_coef`, blocks are applied with a mutable `aux_loss` collection
    and the chunk returns `(y, coef * sum(l_aux))` for the pipeline engine's
    aux accumulator (gating runs rng-free — deterministic — in the rotation;
    the dp parity partner must also run without a gating rng)."""
    from deepspeed_tpu.models.llama import _remat_policy

    def chunk_fn(local_layers, x, aux):
        if moe_aux_coef is None:
            def body(h, layer_params):
                h, _ = block_cls(cfg).apply({"params": layer_params}, h, aux)
                return h, None
        else:
            def body(carry, layer_params):
                h, acc = carry
                (h, _), mut = block_cls(cfg).apply(
                    {"params": layer_params}, h, aux, mutable=["aux_loss"])
                l = jax.tree_util.tree_reduce(
                    lambda a, b: a + jnp.sum(b), mut.get("aux_loss", {}), 0.0)
                return (h, acc + l), None
        if getattr(cfg, "remat", False):
            body = jax.checkpoint(
                body, prevent_cse=False,
                policy=_remat_policy(getattr(cfg, "remat_policy", "nothing")))
        if moe_aux_coef is None:
            return jax.lax.scan(body, x, local_layers)[0]
        # runs inside the pipeline's manual region — the accumulator must be
        # born pipe-varying or the scan carry types mismatch
        acc0 = jax.lax.pcast(jnp.zeros((), jnp.float32), ("pipe",),
                             to="varying")
        (y, acc), _ = jax.lax.scan(body, (x, acc0), local_layers)
        return y, jnp.float32(moe_aux_coef) * acc
    return chunk_fn
