"""Mixtral-style MoE decoder (BASELINE config 4: MoE EP Mixtral-8x7B ZeRO-2).

Counterpart of the reference's mixtral support
(`inference/v2/model_implementations/mixtral`, MoE training via
`deepspeed/moe/`). Llama attention blocks with a top-2 MoE FFN.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import causal_lm_loss, shift_labels
from deepspeed_tpu.models.llama import LlamaAttention, LlamaConfig, RMSNorm
from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.ops.attention import rope_cos_sin
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    capacity_factor: float = 1.25
    max_position_embeddings: int = 4096
    rope_theta: float = 1000000.0
    rms_norm_eps: float = 1e-5
    router_aux_loss_coef: float = 0.02
    remat: bool = True
    remat_policy: str = "nothing"
    attn_impl: str = "auto"
    # MoE dispatch: 'auto' | 'gmm' | 'ragged' | 'einsum' (moe/layer.py)
    dispatch_impl: str = "auto"
    # Explicit per-head width (set by structural head pruning, which
    # shrinks the head COUNT — compression/structured.py).
    head_dim_override: Any = None
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.hidden_size // self.num_attention_heads


PRESETS = {
    "mixtral-8x7b": dict(),
    "mixtral-tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         num_key_value_heads=2, num_local_experts=4,
                         num_experts_per_tok=2, max_position_embeddings=128,
                         remat=False),
}


def mixtral_config(name: str, **overrides) -> MixtralConfig:
    return MixtralConfig(**{**PRESETS[name], **overrides})


def _as_llama(cfg: MixtralConfig) -> LlamaConfig:
    return LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_hidden_layers,
        num_attention_heads=cfg.num_attention_heads,
        num_key_value_heads=cfg.num_key_value_heads,
        max_position_embeddings=cfg.max_position_embeddings,
        rope_theta=cfg.rope_theta, rms_norm_eps=cfg.rms_norm_eps,
        remat=cfg.remat, attn_impl=cfg.attn_impl, dtype=cfg.dtype)


class MixtralBlock(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, h, cos_sin, kv=None):
        cfg = self.cfg
        if kv is not None:
            # inference: no token drops (capacity limits would corrupt
            # generation), no gating noise
            moe = MoE(hidden_size=cfg.hidden_size,
                      num_experts=cfg.num_local_experts,
                      k=cfg.num_experts_per_tok,
                      intermediate_size=cfg.intermediate_size,
                      drop_tokens=False, dtype=cfg.dtype,
                      dispatch_impl=cfg.dispatch_impl,
                      name="block_sparse_moe")
            cos, sin, index, mask = cos_sin
            attn, new_kv = LlamaAttention(_as_llama(cfg), name="self_attn")(
                RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")(h),
                cos, sin, kv=kv, mask=mask, index=index)
            h = h + attn
            h = h + moe(RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                                name="post_attention_layernorm")(h), train=False)
            return h, new_kv
        moe = MoE(hidden_size=cfg.hidden_size, num_experts=cfg.num_local_experts,
                  k=cfg.num_experts_per_tok, intermediate_size=cfg.intermediate_size,
                  capacity_factor=cfg.capacity_factor, dtype=cfg.dtype,
                  dispatch_impl=cfg.dispatch_impl,
                  name="block_sparse_moe")
        cos, sin = cos_sin
        h = shard_along(h, BATCH_AXES, "sequence", None)
        h = h + LlamaAttention(_as_llama(cfg), name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")(h), cos, sin)
        h = h + moe(RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                            name="post_attention_layernorm")(h))
        return h, None


class MixtralForCausalLM(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, cache=None):
        cfg = self.cfg
        embed = self.param("embed_tokens", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0)
        h = shard_along(h, BATCH_AXES, None, None)

        if cache is not None:
            from deepspeed_tpu.inference.kv_cache import decode_mask
            b, s = input_ids.shape
            index = cache.index
            positions = index[:, None] + jnp.arange(s)[None, :]
            cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                                    cfg.dtype)
            mask = decode_mask(positions, cache.max_len)
            ScanBlocks = nn.scan(
                MixtralBlock, variable_axes={"params": 0, "aux_loss": 0},
                split_rngs={"params": True, "gating": True},
                in_axes=(nn.broadcast, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="layers")(
                h, (cos, sin, index, mask), (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(h)
            return self._lm_head(h), new_cache

        h = shard_along(h, BATCH_AXES, "sequence", None)
        positions = jnp.arange(input_ids.shape[1])
        cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta, cfg.dtype)

        block = MixtralBlock
        if cfg.remat:
            from deepspeed_tpu.models.llama import _remat_policy
            block = nn.remat(block, prevent_cse=False,
                             policy=_remat_policy(cfg.remat_policy))
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0, "aux_loss": 0, "metrics": 0},
            split_rngs={"params": True, "gating": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="layers")(h, (cos, sin))
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(h)
        logits = self._lm_head(h)
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}

    def _lm_head(self, h):
        cfg = self.cfg
        lm_head = self.param("lm_head", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("embed", "vocab")),
            (cfg.hidden_size, cfg.vocab_size), jnp.float32)
        return h @ lm_head.astype(cfg.dtype)


def init_mixtral(cfg: MixtralConfig, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = MixtralForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)
    variables = model.init({"params": rng, "gating": rng}, ids)
    raw, specs = extract_params_and_specs({"params": variables["params"]})
    return model, raw, specs


def mixtral_loss_fn(model: MixtralForCausalLM, aux_coef: float = None):
    cfg = model.cfg
    coef = aux_coef if aux_coef is not None else cfg.router_aux_loss_coef

    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = shift_labels(ids)
        rngs = {"gating": rng} if rng is not None else None
        (loss, aux), mut = model.apply(
            {"params": params}, ids, labels=labels, rngs=rngs,
            mutable=["aux_loss", "metrics"])
        l_aux = jax.tree_util.tree_reduce(
            lambda a, b: a + jnp.sum(b), mut.get("aux_loss", {}), 0.0)
        from deepspeed_tpu.models.common import collect_router_metrics
        return loss + coef * l_aux, {"lm_loss": loss, "moe_aux_loss": l_aux,
                                     **collect_router_metrics(mut)}
    return loss_fn


def mixtral_pipeline_fns(model: MixtralForCausalLM):
    """Functional pipeline pieces (see models/llama.py:llama_pipeline_fns).
    The chunk carries the router load-balancing loss (coef pre-applied) out
    of the rotation; gating runs rng-free — pair the pp-vs-dp parity check
    with a gating-rng-free dp loss."""
    from deepspeed_tpu.models.common import apply_rms, make_chunk_fn
    cfg = model.cfg

    def embed_fn(params, ids):
        return jnp.take(params["embed_tokens"].astype(cfg.dtype), ids, axis=0)

    def aux_fn(params, ids):
        return rope_cos_sin(jnp.arange(ids.shape[-1]), cfg.head_dim,
                            cfg.rope_theta, cfg.dtype)

    def head_fn(params, h, ids, labels):
        h = apply_rms(params["norm"], h, cfg.rms_norm_eps, cfg.dtype)
        logits = h @ params["lm_head"].astype(cfg.dtype)
        return causal_lm_loss(logits, ids, labels)

    chunk = make_chunk_fn(MixtralBlock, cfg,
                          moe_aux_coef=cfg.router_aux_loss_coef)
    return embed_fn, aux_fn, chunk, head_fn, "layers", True
