"""GPT-Neo model family (EleutherAI 125M/1.3B/2.7B lineage).

Reference slot: `module_inject/containers/gptneo.py` (DS_GPTNEOContainer,
HFGPTNEOLayerPolicy). Architecture quirks vs GPT-2:
- attention logits are NOT scaled by 1/sqrt(head_dim) (HF
  GPTNeoSelfAttention omits the division) — expressed here by pre-scaling
  q with sqrt(head_dim) so the shared attention core's scale cancels
  exactly;
- layers alternate GLOBAL and LOCAL attention (`attention_types`), local
  = causal sliding window of 256. The per-layer kind rides the nn.scan as
  a scanned 0/1 flag selecting between two precomputed masks, so one
  compiled block body still serves every layer;
- separate q/k/v projections without bias, out/c_fc/c_proj with bias,
  learned absolute positions (wpe), gelu_new MLP, lm_head tied to wte.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import (
    causal_lm_loss, dense as _dense, layer_norm as _ln,
    make_causal_loss_fn)
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


@dataclasses.dataclass(frozen=True)
class GPTNeoConfig:
    vocab_size: int = 50257
    hidden_size: int = 2048
    intermediate_size: int = 8192
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    max_position_embeddings: int = 2048
    window_size: int = 256
    # per-layer attention kind, "global" | "local", length num_hidden_layers
    attention_layers: Tuple[str, ...] = ()
    layer_norm_eps: float = 1e-5
    remat: bool = True
    remat_policy: str = "nothing"
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def layer_kinds(self) -> Tuple[str, ...]:
        if self.attention_layers:
            return self.attention_layers
        # HF default attention_types [[["global","local"], L/2]]
        return tuple(("global", "local")[i % 2]
                     for i in range(self.num_hidden_layers))


PRESETS = {
    "gptneo-1.3b": dict(),
    "gptneo-2.7b": dict(hidden_size=2560, num_hidden_layers=32,
                        num_attention_heads=20, intermediate_size=10240),
    "gptneo-tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_hidden_layers=2, num_attention_heads=4,
                        max_position_embeddings=128, window_size=16,
                        remat=False),
}


def gptneo_config(name: str, **overrides) -> GPTNeoConfig:
    return GPTNeoConfig(**{**PRESETS[name], **overrides})


def _masked_attention(q, k, v, mask):
    """Unscaled masked attention (q is pre-scaled by the caller): the XLA
    path every GPT-Neo layer uses — the traced per-layer mask rules out
    the static-window flash/decode kernels."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits / (d ** 0.5)  # cancels the caller's sqrt(d) pre-scale
    logits = jnp.where(mask[:, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


class GPTNeoAttention(nn.Module):
    cfg: GPTNeoConfig

    @nn.compact
    def __call__(self, h, mask, kv=None, index=None):
        cfg = self.cfg
        hd, nh = cfg.head_dim, cfg.num_attention_heads
        q = _dense(nh * hd, ("embed", "heads"), cfg.dtype, "q_proj")(h)
        k = _dense(nh * hd, ("embed", "kv_heads"), cfg.dtype, "k_proj")(h)
        v = _dense(nh * hd, ("embed", "kv_heads"), cfg.dtype, "v_proj")(h)
        b, s = h.shape[:2]
        # HF GPT-Neo does NOT divide attention logits by sqrt(head_dim);
        # pre-scale q so the shared core's 1/sqrt(d) cancels
        q = (q * (hd ** 0.5)).reshape(b, s, nh, hd)
        k = k.reshape(b, s, nh, hd)
        v = v.reshape(b, s, nh, hd)

        if kv is not None:
            from deepspeed_tpu.inference.kv_cache import update_layer
            from deepspeed_tpu.ops.attention import cached_attention
            k_cache, v_cache = update_layer(kv[0], kv[1], k, v, index)
            # impl='reference' FORCES the elementwise-mask path on BOTH
            # dense and paged caches: the per-layer global/local mask is
            # traced, and the Pallas decode/prefill kernels would apply a
            # `window=` uniformly to every layer — banding the GLOBAL
            # layers too. Correctness over kernel speed for this family.
            ctx = cached_attention(q, k_cache, v_cache, index, mask,
                                   impl="reference")
            out = _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                         "out_proj", use_bias=True)(ctx.reshape(b, s, nh * hd))
            return out, (k_cache, v_cache)

        ctx = _masked_attention(q, k, v, mask)
        return _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                      "out_proj", use_bias=True)(ctx.reshape(b, s, nh * hd))


class GPTNeoMLP(nn.Module):
    cfg: GPTNeoConfig

    @nn.compact
    def __call__(self, h):
        cfg = self.cfg
        up = _dense(cfg.intermediate_size, ("embed", "mlp"), cfg.dtype,
                    "c_fc", use_bias=True)(h)
        return _dense(cfg.hidden_size, ("mlp_in", "embed"), cfg.dtype,
                      "c_proj", use_bias=True)(nn.gelu(up, approximate=True))


class GPTNeoBlock(nn.Module):
    cfg: GPTNeoConfig

    @nn.compact
    def __call__(self, h, aux, local, kv=None):
        """`local` is the SCANNED per-layer 0/1 flag choosing between the
        broadcast (global_mask, local_mask) pair in `aux`."""
        cfg = self.cfg
        if kv is not None:
            (m_global, m_local, index) = aux
            mask = jnp.where(local.astype(bool), m_local, m_global)
            attn, new_kv = GPTNeoAttention(cfg, name="attn")(
                _ln(cfg.layer_norm_eps, cfg.dtype, "ln_1")(h), mask,
                kv=kv, index=index)
            h = h + attn
            h = h + GPTNeoMLP(cfg, name="mlp")(
                _ln(cfg.layer_norm_eps, cfg.dtype, "ln_2")(h))
            return h, new_kv
        m_global, m_local = aux
        mask = jnp.where(local.astype(bool), m_local, m_global)
        h = shard_along(h, BATCH_AXES, "sequence", None)
        h = h + GPTNeoAttention(cfg, name="attn")(
            _ln(cfg.layer_norm_eps, cfg.dtype, "ln_1")(h), mask)
        h = h + GPTNeoMLP(cfg, name="mlp")(
            _ln(cfg.layer_norm_eps, cfg.dtype, "ln_2")(h))
        return h, None


def _train_masks(s: int, window: int):
    i = jnp.arange(s)[:, None]
    j = jnp.arange(s)[None, :]
    causal = j <= i
    band = causal & (j > i - window)
    return causal[None], band[None]  # (1, S, S) broadcast over batch


class GPTNeoForCausalLM(nn.Module):
    cfg: GPTNeoConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, positions=None, cache=None):
        cfg = self.cfg
        locals_ = jnp.asarray(
            [kind == "local" for kind in cfg.layer_kinds], jnp.int32)
        embed = self.param("wte", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        wpe = self.param("wpe", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (None, "embed")),
            (cfg.max_position_embeddings, cfg.hidden_size), jnp.float32)

        if cache is not None:
            from deepspeed_tpu.inference.kv_cache import decode_mask
            b, s = input_ids.shape
            index = cache.index
            positions = index[:, None] + jnp.arange(s)[None, :]
            h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0) + \
                jnp.take(wpe.astype(cfg.dtype), positions, axis=0)
            m_global = decode_mask(positions, cache.max_len)
            m_local = decode_mask(positions, cache.max_len,
                                  window=cfg.window_size)
            ScanBlocks = nn.scan(
                GPTNeoBlock, variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, 0, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="h")(
                h, (m_global, m_local, index), locals_, (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = _ln(cfg.layer_norm_eps, cfg.dtype, "ln_f")(h)
            return h @ embed.astype(cfg.dtype).T, new_cache

        b, s = input_ids.shape
        if positions is None:
            positions = jnp.arange(s)
        h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0) + \
            wpe.astype(cfg.dtype)[positions]
        h = shard_along(h, BATCH_AXES, "sequence", None)
        masks = _train_masks(s, cfg.window_size)
        block = GPTNeoBlock
        if cfg.remat:
            from deepspeed_tpu.models.llama import _remat_policy
            block = nn.remat(block, prevent_cse=False,
                             policy=_remat_policy(cfg.remat_policy))
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0}, split_rngs={"params": True},
            in_axes=(nn.broadcast, 0), length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="h")(h, masks, locals_)
        h = _ln(cfg.layer_norm_eps, cfg.dtype, "ln_f")(h)
        logits = h @ embed.astype(cfg.dtype).T  # tied lm_head
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}


def init_gptneo(cfg: GPTNeoConfig, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = GPTNeoForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)

    def init_fn(rng):
        variables = model.init(rng, ids)
        raw, _ = extract_params_and_specs(variables)
        return raw

    params = jax.jit(init_fn)(rng)
    variables = jax.eval_shape(model.init, rng, ids)
    _, specs = extract_params_and_specs(variables)
    return model, params, specs


def gptneo_loss_fn(model):
    return make_causal_loss_fn(model)

