"""GPT-NeoX model family (pythia lineage).

Reference slot: `module_inject/containers/gptneox.py`. The NeoX block has
TWO LayerNorms whose attention/MLP outputs add onto the residual in
PARALLEL by default (`use_parallel_residual`; False gives the sequential
GPT-J-less variant), partial rotary (`rotary_pct` of head_dim), biased
projections, and an untied `embed_out` head.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import (
    causal_lm_loss, dense as _common_dense, layer_norm as _ln,
    make_causal_loss_fn)
from deepspeed_tpu.models.phi import _partial_rope
from deepspeed_tpu.ops.attention import attention, cached_attention, rope_cos_sin
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


@dataclasses.dataclass(frozen=True)
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 2048
    intermediate_size: int = 8192
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    max_position_embeddings: int = 2048
    rotary_pct: float = 0.25
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    use_parallel_residual: bool = True
    remat: bool = True
    remat_policy: str = "nothing"
    attn_impl: str = "auto"
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def rotary_dim(self) -> int:
        return int(self.rotary_pct * self.head_dim)


PRESETS = {
    "pythia-1b": dict(vocab_size=50304, hidden_size=2048,
                      intermediate_size=8192, num_hidden_layers=16,
                      num_attention_heads=8),
    "pythia-6.9b": dict(vocab_size=50432, hidden_size=4096,
                        intermediate_size=16384, num_hidden_layers=32,
                        num_attention_heads=32),
    "neox-tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=128, remat=False),
}


def gptneox_config(name: str, **overrides) -> GPTNeoXConfig:
    return GPTNeoXConfig(**{**PRESETS[name], **overrides})




class NeoXAttention(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, h, cos, sin, kv=None, mask=None, index=None):
        cfg = self.cfg
        hd, nh = cfg.head_dim, cfg.num_attention_heads
        q = _dense(nh * hd, ("embed", "heads"), cfg.dtype, "q_proj")(h)
        k = _dense(nh * hd, ("embed", "kv_heads"), cfg.dtype, "k_proj")(h)
        v = _dense(nh * hd, ("embed", "kv_heads"), cfg.dtype, "v_proj")(h)
        b, s = h.shape[:2]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nh, hd)
        v = v.reshape(b, s, nh, hd)
        rot = cfg.rotary_dim
        q = _partial_rope(q, cos, sin, rot)
        k = _partial_rope(k, cos, sin, rot)

        if kv is not None:
            from deepspeed_tpu.inference.kv_cache import update_layer
            k_cache, v_cache = update_layer(kv[0], kv[1], k, v, index)
            ctx = cached_attention(q, k_cache, v_cache, index, mask,
                                   impl=cfg.attn_impl)
            out = _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                         "dense")(ctx.reshape(b, s, nh * hd))
            return out, (k_cache, v_cache)

        ctx = attention(q, k, v, causal=True, impl=cfg.attn_impl)
        return _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                      "dense")(ctx.reshape(b, s, nh * hd))


class NeoXMLP(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, h):
        cfg = self.cfg
        up = _dense(cfg.intermediate_size, ("embed", "mlp"), cfg.dtype,
                    "dense_h_to_4h")(h)
        # HF GPT-NeoX default hidden_act="gelu" is EXACT erf gelu
        return _dense(cfg.hidden_size, ("mlp_in", "embed"), cfg.dtype,
                      "dense_4h_to_h")(nn.gelu(up, approximate=False))


class NeoXBlock(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, h, cos_sin, kv=None):
        cfg = self.cfg
        ln1 = _ln(cfg.layer_norm_eps, cfg.dtype, "input_layernorm")
        ln2 = _ln(cfg.layer_norm_eps, cfg.dtype, "post_attention_layernorm")
        if kv is not None:
            cos, sin, index, mask = cos_sin
            attn, new_kv = NeoXAttention(cfg, name="attention")(
                ln1(h), cos, sin, kv=kv, mask=mask, index=index)
            if cfg.use_parallel_residual:
                h = h + attn + NeoXMLP(cfg, name="mlp")(ln2(h))
            else:
                h = h + attn
                h = h + NeoXMLP(cfg, name="mlp")(ln2(h))
            return h, new_kv
        cos, sin = cos_sin
        h = shard_along(h, BATCH_AXES, "sequence", None)
        attn = NeoXAttention(cfg, name="attention")(ln1(h), cos, sin)
        if cfg.use_parallel_residual:
            h = h + attn + NeoXMLP(cfg, name="mlp")(ln2(h))
        else:
            h = h + attn
            h = h + NeoXMLP(cfg, name="mlp")(ln2(h))
        return h, None


class GPTNeoXForCausalLM(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, positions=None, cache=None):
        cfg = self.cfg
        embed = self.param("embed_in", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0)
        h = shard_along(h, BATCH_AXES, "sequence", None)
        rot = cfg.rotary_dim

        if cache is not None:
            from deepspeed_tpu.inference.kv_cache import decode_mask
            b, s = input_ids.shape
            index = cache.index
            positions = index[:, None] + jnp.arange(s)[None, :]
            cos, sin = rope_cos_sin(positions, rot, cfg.rope_theta, cfg.dtype)
            mask = decode_mask(positions, cache.max_len)
            ScanBlocks = nn.scan(
                NeoXBlock, variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="layers")(
                h, (cos, sin, index, mask), (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = _ln(cfg.layer_norm_eps, cfg.dtype, "final_layer_norm")(h)
            return self._lm_head(h), new_cache

        if positions is None:
            positions = jnp.arange(input_ids.shape[1])
        cos, sin = rope_cos_sin(positions, rot, cfg.rope_theta, cfg.dtype)
        block = NeoXBlock
        if cfg.remat:
            from deepspeed_tpu.models.llama import _remat_policy
            block = nn.remat(block, prevent_cse=False,
                             policy=_remat_policy(cfg.remat_policy))
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0}, split_rngs={"params": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="layers")(h, (cos, sin))
        h = _ln(cfg.layer_norm_eps, cfg.dtype, "final_layer_norm")(h)
        logits = self._lm_head(h)
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}

    def _lm_head(self, h):
        cfg = self.cfg
        w = self.param("embed_out", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("embed", "vocab")),
            (cfg.hidden_size, cfg.vocab_size), jnp.float32)
        return h @ w.astype(cfg.dtype)


def init_gptneox(cfg: GPTNeoXConfig, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = GPTNeoXForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)

    def init_fn(rng):
        variables = model.init(rng, ids)
        raw, _ = extract_params_and_specs(variables)
        return raw

    params = jax.jit(init_fn)(rng)
    variables = jax.eval_shape(model.init, rng, ids)
    _, specs = extract_params_and_specs(variables)
    return model, params, specs


def gptneox_loss_fn(model):
    return make_causal_loss_fn(model)


def _dense(features, logical, dtype, name, use_bias: bool = True):
    return _common_dense(features, logical, dtype, name, use_bias=use_bias)


def gptneox_pipeline_fns(model: GPTNeoXForCausalLM):
    """Functional pipeline pieces (see models/llama.py:llama_pipeline_fns)."""
    from deepspeed_tpu.models.common import apply_ln, make_chunk_fn
    cfg = model.cfg

    def embed_fn(params, ids):
        return jnp.take(params["embed_in"].astype(cfg.dtype), ids, axis=0)

    def aux_fn(params, ids):
        return rope_cos_sin(jnp.arange(ids.shape[-1]), cfg.rotary_dim,
                            cfg.rope_theta, cfg.dtype)

    def head_fn(params, h, ids, labels):
        h = apply_ln(params["final_layer_norm"], h, cfg.layer_norm_eps,
                     cfg.dtype)
        logits = h @ params["embed_out"].astype(cfg.dtype)
        return causal_lm_loss(logits, ids, labels)

    return embed_fn, aux_fn, make_chunk_fn(NeoXBlock, cfg), head_fn, "layers"
