"""OPT model family (BASELINE config 3: kernel-injected TP inference
OPT-13B).

Counterpart of the reference's OPT support (`module_inject/containers/
opt.py`, `inference/v2/model_implementations/opt`): learned positions with
OPT's +2 offset, pre-LN decoder (do_layer_norm_before), biased projections,
ReLU FFN, tied lm_head. Same logical-partitioning + nn.scan + KV-cache
conventions as models/llama.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import causal_lm_loss, shift_labels
from deepspeed_tpu.ops.attention import attention
from deepspeed_tpu.sequence.layer import DistributedAttention
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along

POSITION_OFFSET = 2  # HF OPTLearnedPositionalEmbedding offset


@dataclasses.dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    do_layer_norm_before: bool = True
    remat: bool = False
    attn_impl: str = "auto"
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def num_key_value_heads(self) -> int:
        return self.num_attention_heads


PRESETS = {
    "opt-125m": dict(),
    "opt-13b": dict(hidden_size=5120, num_hidden_layers=40,
                    num_attention_heads=40, intermediate_size=20480),
    "opt-tiny": dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=128,
                     max_position_embeddings=128),
}


def opt_config(name: str, **overrides) -> OPTConfig:
    return OPTConfig(**{**PRESETS[name], **overrides})


def _dense(features, logical, cfg, name):
    return nn.Dense(features, use_bias=True, dtype=cfg.dtype,
                    param_dtype=jnp.float32,
                    kernel_init=nn.with_logical_partitioning(
                        nn.initializers.normal(0.02), logical),
                    name=name)


class OPTBlock(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, h, aux, kv=None):
        cfg = self.cfg
        b, s, d = h.shape
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        if kv is None:
            h = shard_along(h, BATCH_AXES, "sequence", None)
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                           name="self_attn_layer_norm")
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                           name="final_layer_norm")
        x = ln1(h) if cfg.do_layer_norm_before else h
        q = _dense(d, ("embed", "heads"), cfg, "q_proj")(x).reshape(b, s, nh, hd)
        k = _dense(d, ("embed", "kv_heads"), cfg, "k_proj")(x).reshape(b, s, nh, hd)
        v = _dense(d, ("embed", "kv_heads"), cfg, "v_proj")(x).reshape(b, s, nh, hd)
        # OPT scales q by 1/sqrt(hd) at projection; equivalent done in attention
        if kv is not None:
            from deepspeed_tpu.inference.kv_cache import update_layer
            from deepspeed_tpu.ops.attention import cached_attention
            index, mask = aux
            k_cache, v_cache = update_layer(kv[0], kv[1], k, v, index)
            ctx = cached_attention(q, k_cache, v_cache, index, mask,
                                   impl=cfg.attn_impl)
            new_kv = (k_cache, v_cache)
        else:
            def core(q, k, v):
                return attention(q, k, v, causal=True, impl=cfg.attn_impl)
            ctx = DistributedAttention(core)(q, k, v)
            new_kv = None
        h = h + _dense(d, ("heads_in", "embed"), cfg, "out_proj")(
            ctx.reshape(b, s, d))
        if not cfg.do_layer_norm_before:
            h = ln1(h)
        x = ln2(h) if cfg.do_layer_norm_before else h
        x = nn.relu(_dense(cfg.intermediate_size, ("embed", "mlp"), cfg, "fc1")(x))
        h = h + _dense(d, ("mlp_in", "embed"), cfg, "fc2")(x)
        if not cfg.do_layer_norm_before:
            h = ln2(h)
        return h, new_kv


class OPTForCausalLM(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, cache=None):
        cfg = self.cfg
        embed = self.param("embed_tokens", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        pos_embed = self.param("embed_positions", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (None, "embed")),
            (cfg.max_position_embeddings + POSITION_OFFSET, cfg.hidden_size),
            jnp.float32)
        b, s = input_ids.shape
        h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0)

        if cache is not None:
            from deepspeed_tpu.inference.kv_cache import decode_mask
            index = cache.index
            positions = index[:, None] + jnp.arange(s)[None, :]
            h = h + jnp.take(pos_embed.astype(cfg.dtype),
                             positions + POSITION_OFFSET, axis=0)
            mask = decode_mask(positions, cache.max_len)
            ScanBlocks = nn.scan(
                OPTBlock, variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="layers")(
                h, (index, mask), (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             name="final_layer_norm")(h)
            logits = jnp.einsum("bsd,vd->bsv", h, embed.astype(cfg.dtype))
            return logits, new_cache

        h = h + pos_embed[POSITION_OFFSET:POSITION_OFFSET + s][None].astype(cfg.dtype)
        h = shard_along(h, BATCH_AXES, "sequence", None)
        block = OPTBlock
        if cfg.remat:
            block = nn.remat(block, prevent_cse=False,
                             policy=jax.checkpoint_policies.nothing_saveable)
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0}, split_rngs={"params": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="layers")(h, None)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="final_layer_norm")(h)
        logits = jnp.einsum("bsd,vd->bsv", h, embed.astype(cfg.dtype))
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}


def init_opt(cfg: OPTConfig, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = OPTForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)
    variables = model.init(rng, ids)
    raw, specs = extract_params_and_specs(variables)
    return model, raw, specs


def opt_loss_fn(model: OPTForCausalLM):
    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = shift_labels(ids)
        return model.apply({"params": params}, ids, labels=labels)
    return loss_fn


def opt_pipeline_fns(model: OPTForCausalLM):
    """Functional pipeline pieces (see models/llama.py:llama_pipeline_fns)."""
    from deepspeed_tpu.models.common import apply_ln, make_chunk_fn
    cfg = model.cfg

    def embed_fn(params, ids):
        s = ids.shape[1]
        h = jnp.take(params["embed_tokens"].astype(cfg.dtype), ids, axis=0)
        return h + params["embed_positions"][
            POSITION_OFFSET:POSITION_OFFSET + s][None].astype(cfg.dtype)

    def aux_fn(params, ids):
        return None

    def head_fn(params, h, ids, labels):
        h = apply_ln(params["final_layer_norm"], h, cfg.layer_norm_eps,
                     cfg.dtype)
        logits = jnp.einsum("bsd,vd->bsv", h,
                            params["embed_tokens"].astype(cfg.dtype))
        return causal_lm_loss(logits, ids, labels)

    return embed_fn, aux_fn, make_chunk_fn(OPTBlock, cfg), head_fn, "layers"
