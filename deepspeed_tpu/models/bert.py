"""BERT model family (bidirectional encoder).

Reference slots: `module_inject/containers/{bert,distil_bert}.py`
(kernel-injection policies), the BERT-era training kernel
(`csrc/transformer/ds_transformer_cuda.cpp` →
`ops/transformer/transformer.py` here), and the BingBertSquad integration
tests. Post-LN encoder: token+position+type embeddings with LN, blocks of
(attention → add&LN → FFN → add&LN), MLM head with transform+LN and a
decoder tied to the word embeddings.

TPU design matches the decoder zoo: `nn.scan` block stack, logical
partitioning for TP, optional remat; attention runs the shared
`ops/attention.py` core with `causal=False`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import (
    cross_entropy_loss, dense as _common_dense, layer_norm as _ln)
from deepspeed_tpu.ops.attention import attention
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    remat: bool = True
    remat_policy: str = "nothing"
    attn_impl: str = "auto"
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


PRESETS = {
    "bert-base": dict(vocab_size=30522, hidden_size=768,
                      intermediate_size=3072, num_hidden_layers=12,
                      num_attention_heads=12),
    "bert-large": dict(vocab_size=30522, hidden_size=1024,
                       intermediate_size=4096, num_hidden_layers=24,
                       num_attention_heads=16),
    "bert-tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      max_position_embeddings=128, remat=False),
}


def bert_config(name: str, **overrides) -> BertConfig:
    return BertConfig(**{**PRESETS[name], **overrides})


def _dense(features, logical, dtype, name):
    return _common_dense(features, logical, dtype, name, use_bias=True)


class BertAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, h, pad_mask):
        cfg = self.cfg
        hd, nh = cfg.head_dim, cfg.num_attention_heads
        q = _dense(nh * hd, ("embed", "heads"), cfg.dtype, "query")(h)
        k = _dense(nh * hd, ("embed", "kv_heads"), cfg.dtype, "key")(h)
        v = _dense(nh * hd, ("embed", "kv_heads"), cfg.dtype, "value")(h)
        b, s = h.shape[:2]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nh, hd)
        v = v.reshape(b, s, nh, hd)
        if pad_mask is not None:
            from deepspeed_tpu.ops.attention import reference_attention
            if s * s > 4096 * 4096:
                raise NotImplementedError(
                    "padding-masked BERT attention materializes (B,H,S,S) "
                    "logits; sequences this long need the unmasked "
                    "blockwise path (pad to full length instead)")
            # (B, Sq, Sk) validity from the padding mask — bidirectional;
            # note cfg.attn_impl does not apply on this masked path
            seg = jnp.broadcast_to(pad_mask[:, None, :], (b, s, s))
            ctx = reference_attention(q, k, v, causal=False, segment_mask=seg)
        else:
            ctx = attention(q, k, v, causal=False, impl=cfg.attn_impl)
        return _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                      "output")(ctx.reshape(b, s, nh * hd))


class BertBlock(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, h, pad_mask):
        cfg = self.cfg
        h = shard_along(h, BATCH_AXES, "sequence", None)
        # post-LN: LayerNorm AFTER each residual add (original BERT)
        attn = BertAttention(cfg, name="attention")(h, pad_mask)
        h = _ln(cfg.layer_norm_eps, cfg.dtype, "attention_layernorm")(h + attn)
        up = _dense(cfg.intermediate_size, ("embed", "mlp"), cfg.dtype,
                    "intermediate")(h)
        down = _dense(cfg.hidden_size, ("mlp_in", "embed"), cfg.dtype,
                      "ffn_output")(nn.gelu(up, approximate=False))
        return _ln(cfg.layer_norm_eps, cfg.dtype, "output_layernorm")(h + down), None


class BertForMaskedLM(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 labels=None):
        cfg = self.cfg
        b, s = input_ids.shape
        word = self.param("word_embeddings", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        pos = self.param("position_embeddings", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), (None, "embed")),
            (cfg.max_position_embeddings, cfg.hidden_size), jnp.float32)
        h = (jnp.take(word.astype(cfg.dtype), input_ids, axis=0)
             + pos.astype(cfg.dtype)[None, :s])
        if cfg.type_vocab_size:  # 0 = DistilBERT (no segment embeddings)
            typ = self.param(
                "token_type_embeddings", nn.with_logical_partitioning(
                    nn.initializers.normal(0.02), (None, "embed")),
                (cfg.type_vocab_size, cfg.hidden_size), jnp.float32)
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            h = h + jnp.take(typ.astype(cfg.dtype), token_type_ids, axis=0)
        h = _ln(cfg.layer_norm_eps, cfg.dtype, "embeddings_layernorm")(h)
        h = shard_along(h, BATCH_AXES, "sequence", None)
        pad_mask = attention_mask.astype(bool) if attention_mask is not None \
            else None

        block = BertBlock
        if cfg.remat:
            from deepspeed_tpu.models.llama import _remat_policy
            block = nn.remat(block, prevent_cse=False,
                             policy=_remat_policy(cfg.remat_policy))
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0}, split_rngs={"params": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="layer")(h, pad_mask)

        # MLM head: transform (dense + gelu + LN) then decoder tied to the
        # word embeddings, plus an output bias
        t = _dense(cfg.hidden_size, ("embed", "embed_out"), cfg.dtype,
                   "transform")(h)
        t = _ln(cfg.layer_norm_eps, cfg.dtype, "transform_layernorm")(
            nn.gelu(t, approximate=False))
        bias = self.param("decoder_bias", nn.with_logical_partitioning(
            nn.initializers.zeros_init(), ("vocab",)),
            (cfg.vocab_size,), jnp.float32)
        logits = jnp.einsum("bsd,vd->bsv", t, word.astype(cfg.dtype)) \
            + bias.astype(cfg.dtype)
        if labels is None:
            return logits
        return cross_entropy_loss(logits, labels), {}


def init_bert(cfg: BertConfig, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = BertForMaskedLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)

    def init_fn(rng):
        variables = model.init(rng, ids)
        raw, _ = extract_params_and_specs(variables)
        return raw

    params = jax.jit(init_fn)(rng)
    variables = jax.eval_shape(model.init, rng, ids)
    _, specs = extract_params_and_specs(variables)
    return model, params, specs


def bert_loss_fn(model: BertForMaskedLM):
    """MLM loss over labels (−100 = unmasked/ignored, HF convention)."""
    def loss_fn(params, batch, rng):
        return model.apply(
            {"params": params}, batch["input_ids"],
            token_type_ids=batch.get("token_type_ids"),
            attention_mask=batch.get("attention_mask"),
            labels=batch["labels"])
    return loss_fn


def bert_pipeline_fns(model: BertForMaskedLM):
    """Functional pipeline pieces for the encoder (see
    models/llama.py:llama_pipeline_fns). Pipeline training assumes full
    attention (no attention_mask padding) and token_type_ids of zeros; MLM
    labels must be supplied in the batch (−100 = ignored)."""
    from deepspeed_tpu.models.common import apply_ln, make_chunk_fn
    cfg = model.cfg

    def embed_fn(params, ids):
        s = ids.shape[1]
        h = (jnp.take(params["word_embeddings"].astype(cfg.dtype), ids, axis=0)
             + params["position_embeddings"].astype(cfg.dtype)[None, :s]
             + params["token_type_embeddings"].astype(cfg.dtype)[0][None, None])
        return apply_ln(params["embeddings_layernorm"], h,
                        cfg.layer_norm_eps, cfg.dtype)

    def aux_fn(params, ids):
        return None  # full attention; padding masks need the dp path

    def head_fn(params, h, ids, labels):
        t = h @ params["transform"]["kernel"].astype(cfg.dtype) + \
            params["transform"]["bias"].astype(cfg.dtype)
        t = apply_ln(params["transform_layernorm"],
                     nn.gelu(t, approximate=False), cfg.layer_norm_eps,
                     cfg.dtype)
        logits = jnp.einsum("bsd,vd->bsv", t,
                            params["word_embeddings"].astype(cfg.dtype)) \
            + params["decoder_bias"].astype(cfg.dtype)
        return cross_entropy_loss(logits, labels)

    return embed_fn, aux_fn, make_chunk_fn(BertBlock, cfg), head_fn, "layer"
