"""Phi model family (Phi-1/1.5/2-style decoder).

Reference slot: `inference/v2/model_implementations/phi` (+ phi3). The Phi
block is PARALLEL: one LayerNorm feeds both attention and MLP and their
outputs add onto the residual together (no post-attention norm); rotary is
PARTIAL (only the first `rotary_dim = partial_rotary_factor * head_dim`
dims rotate); every projection carries bias, including the LM head.

Same TPU design as the llama flagship: `nn.scan` block stack with logical
partitioning, optional remat, shared training/KV-cache parameterization
(per-row cursors from `inference/kv_cache.py`).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import (
    causal_lm_loss, dense as _common_dense, layer_norm as _ln,
    make_causal_loss_fn)
from deepspeed_tpu.ops.attention import (
    apply_rotary_emb, attention, cached_attention, rope_cos_sin)
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


@dataclasses.dataclass(frozen=True)
class PhiConfig:
    vocab_size: int = 51200
    hidden_size: int = 2048
    intermediate_size: int = 8192
    num_hidden_layers: int = 24
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 2048
    partial_rotary_factor: float = 0.5
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    remat: bool = True
    remat_policy: str = "nothing"
    attn_impl: str = "auto"
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def rotary_dim(self) -> int:
        return int(self.partial_rotary_factor * self.head_dim)


PRESETS = {
    "phi-2": dict(vocab_size=51200, hidden_size=2560, intermediate_size=10240,
                  num_hidden_layers=32, num_attention_heads=32,
                  num_key_value_heads=32, max_position_embeddings=2048,
                  partial_rotary_factor=0.4),
    "phi-1_5": dict(vocab_size=51200, hidden_size=2048, intermediate_size=8192,
                    num_hidden_layers=24, num_attention_heads=32,
                    num_key_value_heads=32, max_position_embeddings=2048),
    "phi-tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     num_key_value_heads=4, max_position_embeddings=128,
                     remat=False),
}


def phi_config(name: str, **overrides) -> PhiConfig:
    return PhiConfig(**{**PRESETS[name], **overrides})




def _partial_rope(x, cos, sin, rot):
    if rot >= x.shape[-1]:
        return apply_rotary_emb(x, cos, sin)
    return jnp.concatenate(
        [apply_rotary_emb(x[..., :rot], cos, sin), x[..., rot:]], axis=-1)


class PhiAttention(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, h, cos, sin, kv=None, mask=None, index=None):
        cfg = self.cfg
        hd, nh, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.num_key_value_heads
        q = _dense(nh * hd, ("embed", "heads"), cfg.dtype, "q_proj")(h)
        k = _dense(nkv * hd, ("embed", "kv_heads"), cfg.dtype, "k_proj")(h)
        v = _dense(nkv * hd, ("embed", "kv_heads"), cfg.dtype, "v_proj")(h)
        b, s = h.shape[:2]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nkv, hd)
        v = v.reshape(b, s, nkv, hd)
        rot = cfg.rotary_dim
        q = _partial_rope(q, cos, sin, rot)
        k = _partial_rope(k, cos, sin, rot)

        if kv is not None:
            from deepspeed_tpu.inference.kv_cache import update_layer
            k_cache, v_cache = update_layer(kv[0], kv[1], k, v, index)
            ctx = cached_attention(q, k_cache, v_cache, index, mask,
                                   impl=cfg.attn_impl)
            out = _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                         "dense")(ctx.reshape(b, s, nh * hd))
            return out, (k_cache, v_cache)

        ctx = attention(q, k, v, causal=True, impl=cfg.attn_impl)
        return _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                      "dense")(ctx.reshape(b, s, nh * hd))


class PhiMLP(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, h):
        cfg = self.cfg
        up = _dense(cfg.intermediate_size, ("embed", "mlp"), cfg.dtype, "fc1")(h)
        return _dense(cfg.hidden_size, ("mlp_in", "embed"), cfg.dtype, "fc2")(
            nn.gelu(up, approximate=True))


class PhiBlock(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, h, cos_sin, kv=None):
        cfg = self.cfg
        if kv is not None:
            cos, sin, index, mask = cos_sin
            normed = _ln(cfg.layer_norm_eps, cfg.dtype, "input_layernorm")(h)
            attn, new_kv = PhiAttention(cfg, name="self_attn")(
                normed, cos, sin, kv=kv, mask=mask, index=index)
            h = h + attn + PhiMLP(cfg, name="mlp")(normed)
            return h, new_kv
        cos, sin = cos_sin
        h = shard_along(h, BATCH_AXES, "sequence", None)
        normed = _ln(cfg.layer_norm_eps, cfg.dtype, "input_layernorm")(h)
        h = h + PhiAttention(cfg, name="self_attn")(normed, cos, sin) \
            + PhiMLP(cfg, name="mlp")(normed)
        return h, None


class PhiForCausalLM(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, positions=None, cache=None):
        cfg = self.cfg
        embed = self.param("embed_tokens", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0)
        h = shard_along(h, BATCH_AXES, "sequence", None)
        rot = cfg.rotary_dim

        if cache is not None:
            from deepspeed_tpu.inference.kv_cache import decode_mask
            b, s = input_ids.shape
            index = cache.index
            positions = index[:, None] + jnp.arange(s)[None, :]
            cos, sin = rope_cos_sin(positions, rot, cfg.rope_theta, cfg.dtype)
            mask = decode_mask(positions, cache.max_len)
            ScanBlocks = nn.scan(
                PhiBlock, variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="layers")(
                h, (cos, sin, index, mask), (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = _ln(cfg.layer_norm_eps, cfg.dtype, "final_layernorm")(h)
            logits = self._lm_head(h)
            return logits, new_cache

        if positions is None:
            positions = jnp.arange(input_ids.shape[1])
        cos, sin = rope_cos_sin(positions, rot, cfg.rope_theta, cfg.dtype)
        block = PhiBlock
        if cfg.remat:
            from deepspeed_tpu.models.llama import _remat_policy
            block = nn.remat(block, prevent_cse=False,
                             policy=_remat_policy(cfg.remat_policy))
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0}, split_rngs={"params": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="layers")(h, (cos, sin))
        h = _ln(cfg.layer_norm_eps, cfg.dtype, "final_layernorm")(h)
        logits = self._lm_head(h)
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}

    def _lm_head(self, h):
        cfg = self.cfg
        w = self.param("lm_head", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("embed", "vocab")),
            (cfg.hidden_size, cfg.vocab_size), jnp.float32)
        b = self.param("lm_head_bias", nn.with_logical_partitioning(
            nn.initializers.zeros_init(), ("vocab",)),
            (cfg.vocab_size,), jnp.float32)
        return h @ w.astype(cfg.dtype) + b.astype(cfg.dtype)


def init_phi(cfg: PhiConfig, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = PhiForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)

    def init_fn(rng):
        variables = model.init(rng, ids)
        raw, _ = extract_params_and_specs(variables)
        return raw

    params = jax.jit(init_fn)(rng)
    variables = jax.eval_shape(model.init, rng, ids)
    _, specs = extract_params_and_specs(variables)
    return model, params, specs


def phi_loss_fn(model):
    return make_causal_loss_fn(model)


def _dense(features, logical, dtype, name, use_bias: bool = True):
    return _common_dense(features, logical, dtype, name, use_bias=use_bias)


def phi_pipeline_fns(model: PhiForCausalLM):
    """Functional pipeline pieces (see models/llama.py:llama_pipeline_fns)."""
    from deepspeed_tpu.models.common import apply_ln, make_chunk_fn
    cfg = model.cfg

    def embed_fn(params, ids):
        return jnp.take(params["embed_tokens"].astype(cfg.dtype), ids, axis=0)

    def aux_fn(params, ids):
        return rope_cos_sin(jnp.arange(ids.shape[-1]), cfg.rotary_dim,
                            cfg.rope_theta, cfg.dtype)

    def head_fn(params, h, ids, labels):
        h = apply_ln(params["final_layernorm"], h, cfg.layer_norm_eps,
                     cfg.dtype)
        logits = h @ params["lm_head"].astype(cfg.dtype) + \
            params["lm_head_bias"].astype(cfg.dtype)
        return causal_lm_loss(logits, ids, labels)

    return embed_fn, aux_fn, make_chunk_fn(PhiBlock, cfg), head_fn, "layers"
