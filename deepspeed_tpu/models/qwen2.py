"""Qwen2 model family.

Reference slot: `inference/v2/model_implementations/{qwen,qwen_v2}` and the
fork's own harness (`/root/reference/zero.py:38-60` runs a Qwen 3B HF model
through HfDeepSpeedConfig + ZeRO-3). Qwen2 is the llama decoder skeleton
(RMSNorm, RoPE, GQA, SwiGLU) plus bias on the q/k/v projections — so the
family reuses `LlamaForCausalLM` with `attention_qkv_bias=True`, inheriting
the scan/remat block stack, logical TP rules, KV-cache decode, Ulysses/ring
sequence parallelism, pipeline fns and HF import machinery.
"""

from __future__ import annotations

from deepspeed_tpu.models.llama import (
    LlamaConfig, LlamaForCausalLM, init_params_and_specs, llama_loss_fn,
    llama_pipeline_fns, materialize_params)

Qwen2Config = LlamaConfig          # same schema + attention_qkv_bias=True
Qwen2ForCausalLM = LlamaForCausalLM

PRESETS = {
    # Qwen2.5 sizes (config.json values)
    "qwen2-0.5b": dict(vocab_size=151936, hidden_size=896,
                       intermediate_size=4864, num_hidden_layers=24,
                       num_attention_heads=14, num_key_value_heads=2,
                       max_position_embeddings=32768, rope_theta=1e6,
                       rms_norm_eps=1e-6, tie_word_embeddings=True),
    "qwen2-3b": dict(vocab_size=151936, hidden_size=2048,
                     intermediate_size=11008, num_hidden_layers=36,
                     num_attention_heads=16, num_key_value_heads=2,
                     max_position_embeddings=32768, rope_theta=1e6,
                     rms_norm_eps=1e-6, tie_word_embeddings=True),
    "qwen2-7b": dict(vocab_size=152064, hidden_size=3584,
                     intermediate_size=18944, num_hidden_layers=28,
                     num_attention_heads=28, num_key_value_heads=4,
                     max_position_embeddings=32768, rope_theta=1e6,
                     rms_norm_eps=1e-6),
    "qwen2-tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, max_position_embeddings=128,
                       remat=False),
}


def qwen2_config(name: str, **overrides) -> Qwen2Config:
    return Qwen2Config(**{**PRESETS[name], "attention_qkv_bias": True,
                          **overrides})


__all__ = ["Qwen2Config", "Qwen2ForCausalLM", "qwen2_config", "PRESETS",
           "init_params_and_specs", "materialize_params",
           "llama_pipeline_fns", "llama_loss_fn"]
