"""BLOOM model family.

Reference slot: `module_inject/containers/bloom.py` (kernel-injection
policy) and the alibi path of the inference softmax kernel
(`csrc/transformer/inference/csrc/softmax.cu` — attn softmax w/ alibi).
BLOOM is a sequential-residual LayerNorm decoder with ALiBi positional
bias instead of rotary, an embedding LayerNorm, biased projections and a
tied LM head. Attention uses `ops/attention.py`'s alibi slopes bias
(shift-invariant form, shared by the full and KV-cache paths).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import (
    causal_lm_loss, dense as _common_dense, layer_norm as _ln,
    make_causal_loss_fn)
from deepspeed_tpu.ops.attention import alibi_slopes, attention, cached_attention
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


@dataclasses.dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    max_position_embeddings: int = 2048
    layer_norm_epsilon: float = 1e-5
    remat: bool = True
    remat_policy: str = "nothing"
    attn_impl: str = "auto"
    dtype: Any = jnp.bfloat16
    # alibi bias lives in the logits → decode stays on the masked XLA path;
    # the v2 engine's 'auto' cache layout keys off this (paged decode would
    # gather the dense view every step)
    uses_alibi: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size


PRESETS = {
    "bloom-560m": dict(vocab_size=250880, hidden_size=1024,
                       num_hidden_layers=24, num_attention_heads=16),
    "bloom-7b1": dict(vocab_size=250880, hidden_size=4096,
                      num_hidden_layers=30, num_attention_heads=32),
    "bloom-tiny": dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                       num_attention_heads=4, max_position_embeddings=128,
                       remat=False),
}


def bloom_config(name: str, **overrides) -> BloomConfig:
    return BloomConfig(**{**PRESETS[name], **overrides})




class BloomAttention(nn.Module):
    cfg: BloomConfig

    @nn.compact
    def __call__(self, h, slopes, kv=None, mask=None, index=None):
        cfg = self.cfg
        hd, nh = cfg.head_dim, cfg.num_attention_heads
        q = _dense(nh * hd, ("embed", "heads"), cfg.dtype, "q_proj")(h)
        k = _dense(nh * hd, ("embed", "kv_heads"), cfg.dtype, "k_proj")(h)
        v = _dense(nh * hd, ("embed", "kv_heads"), cfg.dtype, "v_proj")(h)
        b, s = h.shape[:2]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nh, hd)
        v = v.reshape(b, s, nh, hd)

        if kv is not None:
            from deepspeed_tpu.inference.kv_cache import update_layer
            k_cache, v_cache = update_layer(kv[0], kv[1], k, v, index)
            ctx = cached_attention(q, k_cache, v_cache, index, mask,
                                   impl=cfg.attn_impl, alibi=slopes)
            out = _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                         "dense")(ctx.reshape(b, s, nh * hd))
            return out, (k_cache, v_cache)

        ctx = attention(q, k, v, causal=True, impl=cfg.attn_impl, alibi=slopes)
        return _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                      "dense")(ctx.reshape(b, s, nh * hd))


class BloomMLP(nn.Module):
    cfg: BloomConfig

    @nn.compact
    def __call__(self, h):
        cfg = self.cfg
        up = _dense(cfg.intermediate_size, ("embed", "mlp"), cfg.dtype,
                    "dense_h_to_4h")(h)
        return _dense(cfg.hidden_size, ("mlp_in", "embed"), cfg.dtype,
                      "dense_4h_to_h")(nn.gelu(up, approximate=True))


class BloomBlock(nn.Module):
    cfg: BloomConfig

    @nn.compact
    def __call__(self, h, aux, kv=None):
        cfg = self.cfg
        if kv is not None:
            slopes, index, mask = aux
            attn, new_kv = BloomAttention(cfg, name="self_attention")(
                _ln(cfg.layer_norm_epsilon, cfg.dtype, "input_layernorm")(h),
                slopes, kv=kv, mask=mask, index=index)
            h = h + attn
            h = h + BloomMLP(cfg, name="mlp")(
                _ln(cfg.layer_norm_epsilon, cfg.dtype,
                    "post_attention_layernorm")(h))
            return h, new_kv
        slopes, = aux
        h = shard_along(h, BATCH_AXES, "sequence", None)
        h = h + BloomAttention(cfg, name="self_attention")(
            _ln(cfg.layer_norm_epsilon, cfg.dtype, "input_layernorm")(h), slopes)
        h = h + BloomMLP(cfg, name="mlp")(
            _ln(cfg.layer_norm_epsilon, cfg.dtype, "post_attention_layernorm")(h))
        return h, None


class BloomForCausalLM(nn.Module):
    cfg: BloomConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, positions=None, cache=None):
        cfg = self.cfg
        embed = self.param("word_embeddings", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0)
        h = _ln(cfg.layer_norm_epsilon, cfg.dtype,
                "word_embeddings_layernorm")(h)
        h = shard_along(h, BATCH_AXES, "sequence", None)
        slopes = alibi_slopes(cfg.num_attention_heads)

        if cache is not None:
            from deepspeed_tpu.inference.kv_cache import decode_mask
            b, s = input_ids.shape
            index = cache.index
            positions = index[:, None] + jnp.arange(s)[None, :]
            mask = decode_mask(positions, cache.max_len)
            ScanBlocks = nn.scan(
                BloomBlock, variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="h")(
                h, (slopes, index, mask), (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = _ln(cfg.layer_norm_epsilon, cfg.dtype, "ln_f")(h)
            return self._lm_head(h, embed), new_cache

        block = BloomBlock
        if cfg.remat:
            from deepspeed_tpu.models.llama import _remat_policy
            block = nn.remat(block, prevent_cse=False,
                             policy=_remat_policy(cfg.remat_policy))
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0}, split_rngs={"params": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="h")(h, (slopes,))
        h = _ln(cfg.layer_norm_epsilon, cfg.dtype, "ln_f")(h)
        logits = self._lm_head(h, embed)
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}

    def _lm_head(self, h, embed):
        # BLOOM ties the LM head to the word embeddings
        return jnp.einsum("bsd,vd->bsv", h, embed.astype(self.cfg.dtype))


def init_bloom(cfg: BloomConfig, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = BloomForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)

    def init_fn(rng):
        variables = model.init(rng, ids)
        raw, _ = extract_params_and_specs(variables)
        return raw

    params = jax.jit(init_fn)(rng)
    variables = jax.eval_shape(model.init, rng, ids)
    _, specs = extract_params_and_specs(variables)
    return model, params, specs


def bloom_loss_fn(model):
    return make_causal_loss_fn(model)


def _dense(features, logical, dtype, name, use_bias: bool = True):
    return _common_dense(features, logical, dtype, name, use_bias=use_bias)


def bloom_pipeline_fns(model: BloomForCausalLM):
    """Functional pipeline pieces (see models/llama.py:llama_pipeline_fns)."""
    from deepspeed_tpu.models.common import apply_ln, make_chunk_fn
    cfg = model.cfg

    def embed_fn(params, ids):
        h = jnp.take(params["word_embeddings"].astype(cfg.dtype), ids, axis=0)
        return apply_ln(params["word_embeddings_layernorm"], h,
                        cfg.layer_norm_epsilon, cfg.dtype)

    def aux_fn(params, ids):
        return (alibi_slopes(cfg.num_attention_heads),)

    def head_fn(params, h, ids, labels):
        h = apply_ln(params["ln_f"], h, cfg.layer_norm_epsilon, cfg.dtype)
        logits = jnp.einsum("bsd,vd->bsv", h,
                            params["word_embeddings"].astype(cfg.dtype))
        return causal_lm_loss(logits, ids, labels)

    return embed_fn, aux_fn, make_chunk_fn(BloomBlock, cfg), head_fn, "h"
