"""Llama model family (Llama-2/3-style decoder; the flagship training model).

Fills the slot of the reference's model implementations for llama
(`module_inject/containers/llama.py`, `inference/v2/model_implementations/
llama_v2`): RMSNorm + RoPE + GQA attention + SwiGLU MLP, pre-norm decoder.

TPU-first design:
- layers run under `nn.scan` (one compiled block body regardless of depth) +
  optional `nn.remat` (activation checkpointing, reference
  `runtime/activation_checkpointing/checkpointing.py`);
- parameters carry logical axis names; tensor parallelism = the
  'heads'/'mlp'→'model' mapping in `utils/partitioning.DEFAULT_RULES`
  (column-parallel qkv/up, row-parallel out/down — AutoTP's slicing,
  declaratively);
- sequence parallelism via `sequence.layer.DistributedAttention` (Ulysses
  all-to-all) around the attention core;
- attention core is the Pallas flash kernel on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import causal_lm_loss
from deepspeed_tpu.ops.attention import apply_rotary_emb, attention, rope_cos_sin
from deepspeed_tpu.sequence.layer import DistributedAttention
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along

Dtype = Any


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    remat: bool = True
    # jax.checkpoint policy: 'nothing' recomputes the whole block (minimum
    # memory); 'dots' saves matmul outputs (no recompute of MXU work — faster
    # when HBM headroom allows — reference activation_checkpointing's
    # partial-checkpointing knobs).
    remat_policy: str = "nothing"
    attn_impl: str = "auto"
    # When set, training loss runs through the sequence-chunked cross entropy
    # (sequence/cross_entropy.py) and the full (B, S, V) logits are never
    # materialized — required for 128k+ context (BASELINE config 5).
    loss_chunk_size: Optional[int] = None
    # FPDT chunked FFN (reference sequence/fpdt_layer.py:1056): the MLP runs
    # per sequence chunk so its intermediates — ~6·S·I bytes live at once
    # through fwd+bwd, the 128k-ctx OOM after everything else is
    # offloaded/blockwise — peak at chunk granularity instead of S.
    mlp_chunk_size: Optional[int] = None
    # Family variants that share the llama decoder skeleton: Qwen2 adds bias
    # on the q/k/v projections; Mistral bands attention to a sliding window.
    attention_qkv_bias: bool = False
    # InternLM-style bias on the o projection too (HF internlm `bias`)
    attention_o_bias: bool = False
    # Domino two-chunk batch interleave for TP overlap
    # (runtime/domino/transformer.py; measured A/B in
    # benchmarks/domino_ab.py)
    domino: bool = False
    sliding_window: Optional[int] = None
    # Explicit per-head width (HF configs with decoupled head_dim; also set
    # by structural head pruning, which shrinks the head COUNT while each
    # surviving head keeps its width — compression/structured.py).
    head_dim_override: Optional[int] = None
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.hidden_size // self.num_attention_heads


PRESETS = {
    "llama3-8b": dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                      num_hidden_layers=32, num_attention_heads=32,
                      num_key_value_heads=8, max_position_embeddings=8192,
                      rope_theta=500000.0),
    "llama2-7b": dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                      num_hidden_layers=32, num_attention_heads=32,
                      num_key_value_heads=32, max_position_embeddings=4096),
    "llama-1b": dict(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                     num_hidden_layers=22, num_attention_heads=32,
                     num_key_value_heads=4, max_position_embeddings=4096),
    "llama-tiny": dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, max_position_embeddings=128,
                       remat=False),
}


def llama_config(name: str, **overrides) -> LlamaConfig:
    return LlamaConfig(**{**PRESETS[name], **overrides})


def _host_offload_policy(*extra_names: str):
    """save flash_lse in HBM, offload the residual names (+ any extras)
    to pinned host — the single source of truth for the host_offload
    policy family's name lists."""
    return jax.checkpoint_policies.save_and_offload_only_these_names(
        names_which_can_be_saved=["flash_lse"],
        names_which_can_be_offloaded=[
            "fpdt_residual", "flash_resid", *extra_names],
        offload_src="device", offload_dst="pinned_host")


def _remat_policy(name: str):
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if name == "checkpoint_dots":
        # NOTE: do NOT add the named flash residuals here —
        # save_from_both_policies(checkpoint_dots, save_only_these_names(
        # 'flash_resid', 'flash_lse')) measured 18x SLOWER on the 2k-ctx
        # flagship (v5e, r4): the named saves defeat XLA's scheduling of
        # the dots-saved remat graph. The fwd-kernel re-run it would avoid
        # is only ~2% of step FLOPs at 2k ctx; 'host_offload' (long ctx,
        # where the re-run is ~22%) does save/offload them.
        return jax.checkpoint_policies.checkpoint_dots
    if name == "checkpoint_dots_gmm":
        # checkpoint_dots + the named grouped-GEMM outputs (moe/layer.py
        # Experts grouped path): megablox gmm is a Pallas call, not a dot,
        # so without the named save the backward recomputes all three
        # grouped GEMMs per MoE layer. Separate from 'checkpoint_dots'
        # because combined-policy graphs measured pathological with flash
        # names on the dense flagship (r4: 18x) — MoE models opt in.
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.checkpoint_dots,
            jax.checkpoint_policies.save_only_these_names("moe_gmm"))
    if name == "host_offload":
        # FPDT's host-offload tier (reference `sequence/fpdt_layer.py:510`
        # `_FPDTGPUOffloadingAttentionImpl_` / `SequenceChunk:462` CPU↔GPU
        # staging): the per-layer residual-stream checkpoints — the ONLY
        # live activations under whole-block remat, but at 128k ctx ~6 GB
        # across a 24-layer stack — are saved to pinned host memory instead
        # of HBM; XLA schedules the D2H/H2D streams around the block
        # compute. Blocks tag the tensor via checkpoint_name below.
        #
        # 'flash_resid' (ops/pallas/flash_attention.py fwd residuals: the
        # attention output + logsumexp) offloads too: without it, backward
        # re-runs the flash FORWARD kernel per layer just to regenerate lse
        # — at 128k that recompute is ~22% of total attention FLOPs (~6 s
        # of a 36 s step on v5e), far more than the ~0.3 GB/layer of PCIe
        # the offload costs.
        return _host_offload_policy()
    if name == "host_offload_dense":
        # host_offload + the post-rotary q/k/v and the mid-block residual:
        # backward then skips the qkv-GEMM, rotary and o-projection
        # recompute of whole-block remat; ~1 GB/layer extra PCIe.
        # MEASURED LOSING on 1×v5e (r5, 470m @ 32k): 48.1% → 39.9% MFU —
        # the staging does NOT overlap at this volume; PCIe is the
        # bottleneck, not the recompute. Kept for large-HBM parts (v5p)
        # where these names could be saved in HBM via save_names_hbm-style
        # policies instead.
        return _host_offload_policy("attn_qkv", "resid_mid")
    if name == "host_offload_dense_mlp":
        # ...plus the gate/up projections — the FULL dense re-fwd is gone,
        # at ~2 GB/layer more PCIe (the (S, F) pair). MEASURED LOSING
        # HARD on 1×v5e (r5, 470m @ 32k): 48.1% → 23.8% MFU (2.2× slower;
        # see host_offload_dense note).
        return _host_offload_policy("attn_qkv", "resid_mid", "mlp_gate_up")
    if name == "save_names_hbm":
        # whole-block remat with BOTH named residuals saved in HBM — no
        # PCIe staging at all; fits mid-range contexts (≤64k on v5e with
        # host-parked optimizer state)
        return jax.checkpoint_policies.save_only_these_names(
            "flash_lse", "flash_resid", "fpdt_residual")
    if name == "host_offload_flash_hbm":
        # host_offload with the flash residual (attn out) kept in HBM —
        # halves the PCIe staging volume at the cost of ~S·d·2B per layer
        # of HBM; viable when the optimizer state is parked on host
        # (offload_optimizer cpu) so HBM has the headroom.
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=["flash_lse", "flash_resid"],
            names_which_can_be_offloaded=["fpdt_residual"],
            offload_src="device", offload_dst="pinned_host")
    return jax.checkpoint_policies.nothing_saveable


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.with_logical_partitioning(
            nn.initializers.ones_init(), ("embed",)), (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        return ((x32 * jax.lax.rsqrt(var + self.eps)) * w).astype(self.dtype)


def _dense(features, logical, dtype, name, use_bias: bool = False):
    return nn.Dense(features, use_bias=use_bias, dtype=dtype,
                    param_dtype=jnp.float32,
                    kernel_init=nn.with_logical_partitioning(
                        nn.initializers.normal(0.02), logical),
                    bias_init=nn.with_logical_partitioning(
                        nn.initializers.zeros_init(), (logical[-1],)),
                    name=name)


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, h, cos, sin, kv=None, mask=None, index=None):
        cfg = self.cfg
        hd, nh, nkv = cfg.head_dim, cfg.num_attention_heads, cfg.num_key_value_heads
        qb = cfg.attention_qkv_bias  # Qwen2-style qkv bias (o_proj stays bias-free)
        q = _dense(nh * hd, ("embed", "heads"), cfg.dtype, "q_proj", qb)(h)
        k = _dense(nkv * hd, ("embed", "kv_heads"), cfg.dtype, "k_proj", qb)(h)
        v = _dense(nkv * hd, ("embed", "kv_heads"), cfg.dtype, "v_proj", qb)(h)
        b, s = h.shape[:2]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nkv, hd)
        v = v.reshape(b, s, nkv, hd)
        q = apply_rotary_emb(q, cos, sin)
        k = apply_rotary_emb(k, cos, sin)
        if kv is None:
            # post-rotary q/k/v are exactly what flash bwd consumes; the
            # 'host_offload_dense*' policies offload them so backward
            # skips the qkv-GEMM + rotary recompute (identity otherwise)
            from jax.ad_checkpoint import checkpoint_name
            q = checkpoint_name(q, "attn_qkv")
            k = checkpoint_name(k, "attn_qkv")
            v = checkpoint_name(v, "attn_qkv")

        if kv is not None:
            # Decode/prefill against the static KV cache: insert the S new
            # tokens at `index`, attend q over the whole cache under the
            # position mask (inference_context.h / transform.cu:727 analog).
            from deepspeed_tpu.inference.kv_cache import update_layer
            from deepspeed_tpu.ops.attention import cached_attention
            k_cache, v_cache = update_layer(kv[0], kv[1], k, v, index)
            # `window` tells the dispatcher the mask is banded, keeping the
            # prefix-mask-only Pallas decode kernel off that path
            ctx = cached_attention(q, k_cache, v_cache, index, mask,
                                   impl=cfg.attn_impl,
                                   window=cfg.sliding_window)
            out = _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                         "o_proj", cfg.attention_o_bias)(
                ctx.reshape(b, s, nh * hd))
            return out, (k_cache, v_cache)

        if cfg.attn_impl == "ring":
            # context parallelism: KV chunks rotate the sequence ring; no
            # Ulysses head re-sharding (works for any head count)
            assert cfg.sliding_window is None, \
                "ring attention + sliding window not supported"
            from deepspeed_tpu.sequence.ring_attention import RingAttention
            ctx = RingAttention()(q, k, v)
        else:
            def core(q, k, v):
                return attention(q, k, v, causal=True, impl=cfg.attn_impl,
                                 window=cfg.sliding_window)

            ctx = DistributedAttention(core)(q, k, v)
        ctx = ctx.reshape(b, s, nh * hd)
        return _dense(cfg.hidden_size, ("heads_in", "embed"), cfg.dtype,
                      "o_proj", cfg.attention_o_bias)(ctx)


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, h):
        cfg = self.cfg
        gate_d = _dense(cfg.intermediate_size, ("embed", "mlp"), cfg.dtype,
                        "gate_proj")
        up_d = _dense(cfg.intermediate_size, ("embed", "mlp"), cfg.dtype,
                      "up_proj")
        down_d = _dense(cfg.hidden_size, ("mlp_in", "embed"), cfg.dtype,
                        "down_proj")
        from jax.ad_checkpoint import checkpoint_name

        def ffn(hc):
            # gate/up outputs are the S-proportional dot saves that OOM
            # HBM at long context — 'host_offload_dense_mlp' offloads the
            # named tensors instead so backward skips both GEMM recomputes
            g = checkpoint_name(gate_d(hc), "mlp_gate_up")
            u = checkpoint_name(up_d(hc), "mlp_gate_up")
            return down_d(nn.silu(g) * u)
        cs = cfg.mlp_chunk_size
        if not cs or h.shape[1] <= cs or h.shape[1] % cs:
            return ffn(h)
        # FPDT chunked FFN: static unroll over sequence chunks — the MLP is
        # positionwise, so this is exact; each chunk's (cs, I) intermediates
        # die before the next chunk's are born (fwd AND transposed bwd)
        outs = [ffn(hc) for hc in jnp.split(h, h.shape[1] // cs, axis=1)]
        return jnp.concatenate(outs, axis=1)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, h, cos_sin, kv=None):
        cfg = self.cfg
        if kv is not None:
            cos, sin, index, mask = cos_sin
            attn, new_kv = LlamaAttention(cfg, name="self_attn")(
                RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")(h),
                cos, sin, kv=kv, mask=mask, index=index)
            h = h + attn
            h = h + LlamaMLP(cfg, name="mlp")(
                RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                        name="post_attention_layernorm")(h))
            return h, new_kv
        cos, sin = cos_sin
        h = shard_along(h, BATCH_AXES, "sequence", None)
        # name the block-boundary residual so the 'host_offload' remat
        # policy can stage it to pinned host memory (no-op otherwise)
        from jax.ad_checkpoint import checkpoint_name
        h = checkpoint_name(h, "fpdt_residual")
        attn = LlamaAttention(cfg, name="self_attn")
        ln1 = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")
        mlp = LlamaMLP(cfg, name="mlp")
        ln2 = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                      name="post_attention_layernorm")
        if cfg.domino and h.shape[0] >= 2:
            # Domino (runtime/domino/transformer.py): interleave two batch
            # halves so each half's TP output-allreduce has the OTHER
            # half's compute to overlap with — same params (shared module
            # instances), numerically exact (batch dim is data-parallel
            # within the layer).
            b = h.shape[0]
            x0, x1 = h[: b // 2], h[b // 2:]
            a0 = attn(ln1(x0), cos, sin)
            a1 = attn(ln1(x1), cos, sin)
            h0 = checkpoint_name(x0 + a0, "resid_mid")
            m0 = mlp(ln2(h0))
            h1 = checkpoint_name(x1 + a1, "resid_mid")
            m1 = mlp(ln2(h1))
            return jnp.concatenate([h0 + m0, h1 + m1], axis=0), None
        h = h + attn(ln1(h), cos, sin)
        # mid-block residual: saving it lets backward rebuild mlp_normed
        # with one cheap RMSNorm instead of re-running the o-projection
        h = checkpoint_name(h, "resid_mid")
        h = h + mlp(ln2(h))
        return h, None


class LlamaForCausalLM(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, positions=None, cache=None):
        cfg = self.cfg
        embed = self.param("embed_tokens", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0)
        h = shard_along(h, BATCH_AXES, "sequence", None)

        if cache is not None:
            # Cached decode/prefill path (reference inference/engine.py:579):
            # same params, scan carries KV through the stacked layer cache.
            from deepspeed_tpu.inference.kv_cache import decode_mask
            b, s = input_ids.shape
            index = cache.index  # (B,) per-sequence cursors
            positions = index[:, None] + jnp.arange(s)[None, :]  # (B, S)
            cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                                    cfg.dtype)
            mask = decode_mask(positions, cache.max_len,
                               window=cfg.sliding_window)
            ScanBlocks = nn.scan(
                LlamaBlock, variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="layers")(
                h, (cos, sin, index, mask), (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(h)
            logits = self._lm_head(h, embed)
            return logits, new_cache

        if positions is None:
            positions = jnp.arange(input_ids.shape[1])
        cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta, cfg.dtype)

        block = LlamaBlock
        if cfg.remat:
            block = nn.remat(block, prevent_cse=False,
                             policy=_remat_policy(cfg.remat_policy))
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0}, split_rngs={"params": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="layers")(h, (cos, sin))
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(h)

        if labels is not None and cfg.loss_chunk_size:
            from deepspeed_tpu.sequence.cross_entropy import (
                chunked_softmax_cross_entropy)
            if cfg.tie_word_embeddings:
                w, tied = embed.astype(cfg.dtype), True
            else:
                w = self.param("lm_head", nn.with_logical_partitioning(
                    nn.initializers.normal(0.02), ("embed", "vocab")),
                    (cfg.hidden_size, cfg.vocab_size), jnp.float32)
                w, tied = w.astype(cfg.dtype), False
            loss = chunked_softmax_cross_entropy(
                h, w, labels, chunk_size=cfg.loss_chunk_size, tied_embedding=tied)
            return loss, {}

        logits = self._lm_head(h, embed)
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}

    def _lm_head(self, h, embed):
        cfg = self.cfg
        if cfg.tie_word_embeddings:
            return jnp.einsum("bsd,vd->bsv", h, embed.astype(cfg.dtype))
        lm_head = self.param("lm_head", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("embed", "vocab")),
            (cfg.hidden_size, cfg.vocab_size), jnp.float32)
        return h @ lm_head.astype(cfg.dtype)


def init_params_and_specs(cfg: LlamaConfig, rng=None, seq_len: int = 8):
    """Abstract-init → (param ShapeDtypeStructs or arrays, PartitionSpec tree)."""
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = LlamaForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)
    variables = jax.eval_shape(model.init, rng, ids)
    _, specs = extract_params_and_specs(variables)
    return model, specs


def materialize_params(cfg: LlamaConfig, rng=None, seq_len: int = 8,
                       shardings=None):
    """Initialize real parameters (optionally directly into shardings)."""
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = LlamaForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)

    def init_fn(rng):
        variables = model.init(rng, ids)
        raw, _ = extract_params_and_specs(variables)
        return raw

    if shardings is not None:
        return model, jax.jit(init_fn, out_shardings=shardings)(rng)
    # Always trace under jit: activation sharding constraints are lenient
    # inside jit (padding), but error eagerly outside it when a topology is
    # installed whose data axis doesn't divide the tiny trace batch.
    return model, jax.jit(init_fn)(rng)


def llama_pipeline_fns(model: LlamaForCausalLM):
    """Functional (embed, aux, chunk, head) pieces for the pipeline engine.

    The block stack stays the `LlamaBlock` module (applied per layer inside
    the stage rotation); embed/head replicate `__call__`'s exact math on the
    raw param tree so pp=1 and pp>1 trajectories agree bit-for-bit.
    """
    cfg = model.cfg

    def embed_fn(params, ids):
        return jnp.take(params["embed_tokens"].astype(cfg.dtype), ids, axis=0)

    def aux_fn(params, ids):
        positions = jnp.arange(ids.shape[-1])
        return rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta, cfg.dtype)

    def chunk_fn(local_layers, x, aux):
        def body(h, layer_params):
            h, _ = LlamaBlock(cfg).apply({"params": layer_params}, h, aux)
            return h, None
        if cfg.remat:
            body = jax.checkpoint(body, prevent_cse=False,
                                  policy=_remat_policy(cfg.remat_policy))
        return jax.lax.scan(body, x, local_layers)[0]

    def head_fn(params, h, ids, labels):
        w = params["norm"]["weight"]
        x32 = h.astype(jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        h = ((x32 * jax.lax.rsqrt(var + cfg.rms_norm_eps)) * w).astype(cfg.dtype)
        if cfg.tie_word_embeddings:
            logits = jnp.einsum("bsd,vd->bsv", h,
                                params["embed_tokens"].astype(cfg.dtype))
        else:
            logits = h @ params["lm_head"].astype(cfg.dtype)
        return causal_lm_loss(logits, ids, labels)

    return embed_fn, aux_fn, chunk_fn, head_fn, "layers"


def llama_loss_fn(model: LlamaForCausalLM):
    from deepspeed_tpu.models.common import shift_labels

    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = shift_labels(ids)
        return model.apply({"params": params}, ids, labels=labels)
    return loss_fn
