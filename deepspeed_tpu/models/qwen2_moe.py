"""Qwen2-MoE model family (Qwen1.5-MoE-A2.7B lineage).

Reference slot: `inference/v2/model_implementations/qwen_v2_moe` — the last
v2 model family. The block is the mixtral MoE decoder with Qwen2's
qkv-bias attention plus a SHARED expert: a dense SwiGLU MLP applied to
every token, gated per-token by sigmoid(shared_expert_gate(h)), added to
the routed-experts output. The router can keep raw softmax top-k weights
(HF `norm_topk_prob=False`) via the gate's `norm_topk_prob` knob.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.common import causal_lm_loss, dense as _dense
from deepspeed_tpu.models.llama import LlamaAttention, LlamaConfig, RMSNorm
from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.ops.attention import rope_cos_sin
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


@dataclasses.dataclass(frozen=True)
class Qwen2MoeConfig:
    vocab_size: int = 151936
    hidden_size: int = 2048
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    num_experts: int = 60
    num_experts_per_tok: int = 4
    moe_intermediate_size: int = 1408
    shared_expert_intermediate_size: int = 5632
    norm_topk_prob: bool = False
    capacity_factor: float = 1.25
    router_aux_loss_coef: float = 0.001
    max_position_embeddings: int = 8192
    rope_theta: float = 1000000.0
    rms_norm_eps: float = 1e-6
    remat: bool = True
    remat_policy: str = "nothing"
    attn_impl: str = "auto"
    # MoE dispatch: 'auto' | 'gmm' | 'ragged' | 'einsum' (moe/layer.py)
    dispatch_impl: str = "auto"
    # Explicit per-head width (set by structural head pruning, which
    # shrinks the head COUNT — compression/structured.py).
    head_dim_override: Any = None
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.hidden_size // self.num_attention_heads


PRESETS = {
    "qwen1.5-moe-a2.7b": dict(),
    "qwen2moe-tiny": dict(vocab_size=256, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, num_experts=4,
                          num_experts_per_tok=2, moe_intermediate_size=32,
                          shared_expert_intermediate_size=128,
                          max_position_embeddings=128, remat=False),
}


def qwen2_moe_config(name: str, **overrides) -> Qwen2MoeConfig:
    return Qwen2MoeConfig(**{**PRESETS[name], **overrides})


def _as_llama(cfg: Qwen2MoeConfig) -> LlamaConfig:
    return LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        intermediate_size=cfg.shared_expert_intermediate_size,
        num_hidden_layers=cfg.num_hidden_layers,
        num_attention_heads=cfg.num_attention_heads,
        num_key_value_heads=cfg.num_key_value_heads,
        max_position_embeddings=cfg.max_position_embeddings,
        rope_theta=cfg.rope_theta, rms_norm_eps=cfg.rms_norm_eps,
        attention_qkv_bias=True,  # the Qwen2 attention variant
        remat=cfg.remat, attn_impl=cfg.attn_impl, dtype=cfg.dtype)


class SharedExpert(nn.Module):
    """Dense SwiGLU applied to every token, sigmoid-gated per token."""
    cfg: Qwen2MoeConfig

    @nn.compact
    def __call__(self, h):
        cfg = self.cfg
        f = cfg.shared_expert_intermediate_size
        gate = _dense(f, ("embed", "mlp"), cfg.dtype, "gate_proj")(h)
        up = _dense(f, ("embed", "mlp"), cfg.dtype, "up_proj")(h)
        out = _dense(cfg.hidden_size, ("mlp_in", "embed"), cfg.dtype,
                     "down_proj")(nn.silu(gate) * up)
        g = _dense(1, ("embed", None), cfg.dtype, "shared_expert_gate")(h)
        return jax.nn.sigmoid(g.astype(jnp.float32)).astype(out.dtype) * out


class Qwen2MoeBlock(nn.Module):
    cfg: Qwen2MoeConfig

    @nn.compact
    def __call__(self, h, cos_sin, kv=None):
        cfg = self.cfg

        def moe(drop):
            return MoE(hidden_size=cfg.hidden_size, num_experts=cfg.num_experts,
                       k=cfg.num_experts_per_tok,
                       intermediate_size=cfg.moe_intermediate_size,
                       capacity_factor=cfg.capacity_factor,
                       drop_tokens=drop, norm_topk_prob=cfg.norm_topk_prob,
                       dispatch_impl=cfg.dispatch_impl,
                       dtype=cfg.dtype, name="mlp")

        if kv is not None:
            cos, sin, index, mask = cos_sin
            attn, new_kv = LlamaAttention(_as_llama(cfg), name="self_attn")(
                RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")(h),
                cos, sin, kv=kv, mask=mask, index=index)
            h = h + attn
            normed = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                             name="post_attention_layernorm")(h)
            h = h + moe(drop=False)(normed, train=False) \
                + SharedExpert(cfg, name="shared_expert")(normed)
            return h, new_kv
        cos, sin = cos_sin
        h = shard_along(h, BATCH_AXES, "sequence", None)
        h = h + LlamaAttention(_as_llama(cfg), name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")(h),
            cos, sin)
        normed = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                         name="post_attention_layernorm")(h)
        h = h + moe(drop=True)(normed) \
            + SharedExpert(cfg, name="shared_expert")(normed)
        return h, None


class Qwen2MoeForCausalLM(nn.Module):
    cfg: Qwen2MoeConfig

    @nn.compact
    def __call__(self, input_ids, labels=None, cache=None):
        cfg = self.cfg
        embed = self.param("embed_tokens", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        h = jnp.take(embed.astype(cfg.dtype), input_ids, axis=0)
        h = shard_along(h, BATCH_AXES, None, None)

        if cache is not None:
            from deepspeed_tpu.inference.kv_cache import decode_mask
            b, s = input_ids.shape
            index = cache.index
            positions = index[:, None] + jnp.arange(s)[None, :]
            cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                                    cfg.dtype)
            mask = decode_mask(positions, cache.max_len)
            ScanBlocks = nn.scan(
                Qwen2MoeBlock, variable_axes={"params": 0, "aux_loss": 0},
                split_rngs={"params": True, "gating": True},
                in_axes=(nn.broadcast, 0), out_axes=0,
                length=cfg.num_hidden_layers,
                metadata_params={nn.meta.PARTITION_NAME: "layers"})
            h, (k_new, v_new) = ScanBlocks(cfg, name="layers")(
                h, (cos, sin, index, mask), (cache.k, cache.v))
            new_cache = cache.replace(k=k_new, v=v_new, index=index + s)
            h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(h)
            return self._lm_head(h), new_cache

        positions = jnp.arange(input_ids.shape[1])
        cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta, cfg.dtype)
        block = Qwen2MoeBlock
        if cfg.remat:
            from deepspeed_tpu.models.llama import _remat_policy
            block = nn.remat(block, prevent_cse=False,
                             policy=_remat_policy(cfg.remat_policy))
        ScanBlocks = nn.scan(
            block, variable_axes={"params": 0, "aux_loss": 0, "metrics": 0},
            split_rngs={"params": True, "gating": True},
            in_axes=nn.broadcast, length=cfg.num_hidden_layers,
            metadata_params={nn.meta.PARTITION_NAME: "layers"})
        h, _ = ScanBlocks(cfg, name="layers")(h, (cos, sin))
        h = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(h)
        logits = self._lm_head(h)
        if labels is None:
            return logits
        return causal_lm_loss(logits, input_ids, labels), {}

    def _lm_head(self, h):
        cfg = self.cfg
        w = self.param("lm_head", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("embed", "vocab")),
            (cfg.hidden_size, cfg.vocab_size), jnp.float32)
        return h @ w.astype(cfg.dtype)


def init_qwen2_moe(cfg: Qwen2MoeConfig, rng=None, seq_len: int = 8):
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    model = Qwen2MoeForCausalLM(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ids = jnp.zeros((1, seq_len), jnp.int32)
    variables = model.init({"params": rng, "gating": rng}, ids)
    raw, specs = extract_params_and_specs({"params": variables["params"]})
    return model, raw, specs


def qwen2_moe_loss_fn(model: Qwen2MoeForCausalLM, aux_coef: float = None):
    from deepspeed_tpu.models.common import shift_labels
    coef = aux_coef if aux_coef is not None else model.cfg.router_aux_loss_coef

    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = shift_labels(ids)
        rngs = {"gating": rng} if rng is not None else None
        (loss, aux), mut = model.apply(
            {"params": params}, ids, labels=labels, rngs=rngs,
            mutable=["aux_loss", "metrics"])
        l_aux = jax.tree_util.tree_reduce(
            lambda a, b: a + jnp.sum(b), mut.get("aux_loss", {}), 0.0)
        from deepspeed_tpu.models.common import collect_router_metrics
        return loss + coef * l_aux, {"lm_loss": loss, "moe_aux_loss": l_aux,
                                     **collect_router_metrics(mut)}
    return loss_fn


def qwen2_moe_pipeline_fns(model: Qwen2MoeForCausalLM):
    """Functional pipeline pieces (see models/mixtral.py:mixtral_pipeline_fns
    — same MoE aux-loss threading, rng-free gating)."""
    from deepspeed_tpu.models.common import apply_rms, make_chunk_fn
    cfg = model.cfg

    def embed_fn(params, ids):
        return jnp.take(params["embed_tokens"].astype(cfg.dtype), ids, axis=0)

    def aux_fn(params, ids):
        return rope_cos_sin(jnp.arange(ids.shape[-1]), cfg.head_dim,
                            cfg.rope_theta, cfg.dtype)

    def head_fn(params, h, ids, labels):
        h = apply_rms(params["norm"], h, cfg.rms_norm_eps, cfg.dtype)
        logits = h @ params["lm_head"].astype(cfg.dtype)
        return causal_lm_loss(logits, ids, labels)

    chunk = make_chunk_fn(Qwen2MoeBlock, cfg,
                          moe_aux_coef=cfg.router_aux_loss_coef)
    return embed_fn, aux_fn, chunk, head_fn, "layers", True
