"""Self-speculative draft construction — a layer slice sharing the checkpoint.

The cheap draft for speculative decoding (inference/speculative.py) is the
TARGET model with most of its layer stack removed: the zoo models are
`nn.scan` block stacks, so "remove layers" is `jnp.take` on the stacked
axis — the same operation the structural-compression layer reduction uses
(compression/structured.py) — and the draft shares the checkpoint's
embed/norm/head verbatim. No second model is trained, imported or stored:
the draft params are a GATHER of the target params, cheap enough to build
in-program (loop-invariant — XLA hoists it out of the decode loop).

Layer choice: evenly spaced indices that always keep the FIRST and LAST
block (`self_draft_layers`). First/last carry the embedding lift-off and
the pre-head representation; evenly spacing the middle keeps the residual
stream's depth profile — the standard self-speculative recipe. It is a
heuristic, not a guarantee: acceptance rate is measured per model
(telemetry `acceptance_rate`), and callers can pass an explicit index list
instead.

Family coverage is duck-typed: the stacked subtree is named `layers` in
the llama lineage but `h` in gpt2 (`nn.scan(..., name="h")`), so
`layer_stack_key` detects it by shape — the top-level subtree whose every
array leaf carries the layer count as its leading dim.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def num_layers_of(cfg) -> int:
    """Layer count, duck-typed over zoo config naming."""
    n = getattr(cfg, "num_hidden_layers", None) or getattr(cfg, "n_layer")
    return int(n)


def self_draft_layers(num_layers: int, keep: int) -> Tuple[int, ...]:
    """`keep` evenly spaced layer indices out of `num_layers`, always
    including the first and last layer (keep == 1 degenerates to layer 0).
    Strictly increasing — with keep <= num_layers the linspace stride is
    >= 1, so rounding never collides."""
    if not 1 <= keep <= num_layers:
        raise ValueError(
            f"speculative: draft_layers resolves to {keep} layers, expected "
            f"1..{num_layers}")
    if keep == 1:
        return (0,)
    pts = np.linspace(0, num_layers - 1, keep)
    return tuple(int(round(p)) for p in pts)


def resolve_draft_layers(num_layers: int, spec_layers: Any) -> Tuple[int, ...]:
    """`draft_layers` config value → concrete indices: a float is a depth
    ratio (0.5 → half the layers), an int is a layer count, a list/tuple is
    the explicit indices."""
    if isinstance(spec_layers, (list, tuple)):
        idx = tuple(int(i) for i in spec_layers)
        if not idx or any(not 0 <= i < num_layers for i in idx) \
                or list(idx) != sorted(set(idx)):
            raise ValueError(
                f"speculative: draft_layers {spec_layers!r} must be strictly "
                f"increasing indices in 0..{num_layers - 1}")
        return idx
    if isinstance(spec_layers, float):
        return self_draft_layers(num_layers,
                                 max(1, int(round(num_layers * spec_layers))))
    return self_draft_layers(num_layers, int(spec_layers))


def layer_stack_key(params: Any, num_layers: int) -> str:
    """The top-level key of the stacked layer subtree ('layers' for the
    llama lineage, 'h' for gpt2) — detected by shape: every array leaf
    under it must carry `num_layers` as its leading dim. Known names are
    tried first so a coincidental num_layers-row leaf elsewhere can't win."""
    if not isinstance(params, dict):
        raise ValueError("speculative: self-draft needs a dict param tree")
    candidates = [k for k in ("layers", "h") if k in params]
    candidates += [k for k in params if k not in ("layers", "h")]
    for key in candidates:
        sub = params[key]
        if not isinstance(sub, dict):
            continue
        leaves = jax.tree_util.tree_leaves(sub)
        if leaves and all(getattr(x, "ndim", 0) >= 1
                          and x.shape[0] == num_layers for x in leaves):
            return key
    raise ValueError(
        "speculative: draft='self' needs an nn.scan-stacked param tree "
        "(no subtree with a leading layer axis found); pass a draft model "
        "via draft='model' instead")


def take_layer_stack(params: dict, stack_key: str,
                     idx: jnp.ndarray) -> dict:
    """The draft's param tree: the target tree with the stacked subtree
    gathered at `idx` (embed/norm/head and every other leaf SHARED, not
    copied). jit-safe — the dequant serve path runs this in-program, where
    it is loop-invariant and costs one gather per program."""
    sliced = jax.tree_util.tree_map(lambda x: jnp.take(x, idx, axis=0),
                                    params[stack_key])
    out = dict(params)
    out[stack_key] = sliced
    return out


def make_draft_module(model: Any, num_draft_layers: int) -> Any:
    """The draft's flax module: the target module with its config's layer
    count replaced (frozen dataclass → `dataclasses.replace`). Everything
    else — dims, rope, norm eps, tied head — is inherited, which is what
    makes the sliced target params a valid param tree for it."""
    cfg = model.cfg
    field = ("num_hidden_layers"
             if getattr(cfg, "num_hidden_layers", None) is not None
             else "n_layer")
    draft_cfg = dataclasses.replace(cfg, **{field: int(num_draft_layers)})
    return model.clone(cfg=draft_cfg)
