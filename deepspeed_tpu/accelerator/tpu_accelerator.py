"""TPU accelerator (the primary backend).

Fills the slot of the reference's `accelerator/cuda_accelerator.py`: device
enumeration, memory stats, and peak-FLOPs tables per TPU generation. The
communication backend name is `xla` — collectives ride ICI/DCN via XLA
(see `deepspeed_tpu/comm`), the counterpart of NCCL selection at
reference `accelerator/cuda_accelerator.py:communication_backend_name`.
"""

from __future__ import annotations

from typing import Any, List

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator

# Dense peak TFLOP/s per chip (bf16), public spec-sheet numbers.
_TPU_PEAK_TFLOPS_BF16 = {
    "v2": 45.0,
    "v3": 123.0,
    "v4": 275.0,
    "v5e": 197.0,
    "v5 lite": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
    "v6 lite": 918.0,
}

# HBM bandwidth per chip (GB/s), public spec-sheet numbers — the roofline
# denominator for the program ledger's HBM-bound predictions.
_TPU_HBM_GBPS = {
    "v2": 700.0,
    "v3": 900.0,
    "v4": 1228.0,
    "v5e": 819.0,
    "v5 lite": 819.0,
    "v5p": 2765.0,
    "v6e": 1640.0,
    "v6 lite": 1640.0,
}

# HBM per chip (bytes), public spec-sheet numbers — the fallback when the
# runtime reports no memory stats (the axon tunnel returns {} — without
# this the autotuner's OOM pruning silently disables itself).
_TPU_HBM_BYTES = {
    "v2": 8 << 30,
    "v3": 16 << 30,
    "v4": 32 << 30,
    "v5e": 16 << 30,
    "v5 lite": 16 << 30,
    "v5p": 95 << 30,
    "v6e": 32 << 30,
    "v6 lite": 32 << 30,
}


class TPU_Accelerator(DeepSpeedAccelerator):
    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"

    def is_synchronized_device(self) -> bool:
        return False

    def devices(self) -> List[Any]:
        import jax
        return [d for d in jax.devices() if d.platform in ("tpu", "axon")]

    def local_device_count(self) -> int:
        import jax
        return jax.local_device_count()

    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    def device_kind(self) -> str:
        devs = self.devices()
        return devs[0].device_kind if devs else "unknown"

    def peak_tflops(self, dtype: str = "bfloat16") -> float:
        kind = self.device_kind().lower()
        for key, tflops in _TPU_PEAK_TFLOPS_BF16.items():
            if key in kind:
                if dtype in ("int8", "fp8"):
                    return tflops * 2
                return tflops
        return 197.0  # default to v5e if unrecognized

    def peak_hbm_gbps(self) -> float:
        kind = self.device_kind().lower()
        for key, gbps in _TPU_HBM_GBPS.items():
            if key in kind:
                return gbps
        return 819.0  # default to v5e if unrecognized

    def total_memory(self, device_index=None) -> int:
        reported = self.memory_stats(device_index).get("bytes_limit", 0)
        if reported:
            return reported
        kind = self.device_kind().lower()
        for key, hbm in _TPU_HBM_BYTES.items():
            if key in kind:
                return hbm
        return 16 << 30  # default to v5e if unrecognized

    def is_available(self) -> bool:
        return len(self.devices()) > 0


class CPU_Accelerator(DeepSpeedAccelerator):
    """CPU backend for tests and host-side work (reference: accelerator/cpu_accelerator.py)."""

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "gloo"

    def is_synchronized_device(self) -> bool:
        return True

    def devices(self) -> List[Any]:
        import jax
        return [d for d in jax.devices() if d.platform == "cpu"]

    def local_device_count(self) -> int:
        return len(self.devices())

    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    def peak_tflops(self, dtype: str = "bfloat16") -> float:
        return 1.0

    def peak_hbm_gbps(self) -> float:
        return 50.0  # nominal DDR bandwidth; CPU rooflines are proxies

    def is_available(self) -> bool:
        return True
