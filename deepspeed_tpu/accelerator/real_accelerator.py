"""Accelerator selection.

Counterpart of reference `accelerator/real_accelerator.py:51`
(`get_accelerator`): honors the `DS_ACCELERATOR` env override, otherwise
auto-detects TPU and falls back to CPU.
"""

from __future__ import annotations

import os
from typing import Optional

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.accelerator.tpu_accelerator import CPU_Accelerator, TPU_Accelerator
from deepspeed_tpu.utils.logging import logger

_accelerator: Optional[DeepSpeedAccelerator] = None

SUPPORTED_ACCELERATOR_LIST = ["tpu", "cpu"]


def get_accelerator() -> DeepSpeedAccelerator:
    global _accelerator
    if _accelerator is not None:
        return _accelerator

    accelerator_name = os.environ.get("DS_ACCELERATOR")
    if accelerator_name is not None:
        accelerator_name = accelerator_name.lower()
        if accelerator_name not in SUPPORTED_ACCELERATOR_LIST:
            raise ValueError(
                f"DS_ACCELERATOR={accelerator_name} not in {SUPPORTED_ACCELERATOR_LIST}")
    else:
        tpu = TPU_Accelerator()
        accelerator_name = "tpu" if tpu.is_available() else "cpu"

    _accelerator = TPU_Accelerator() if accelerator_name == "tpu" else CPU_Accelerator()
    logger.debug(f"Setting accelerator to {accelerator_name}")
    return _accelerator


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _accelerator
    _accelerator = accel


def is_current_accelerator_supported() -> bool:
    return get_accelerator()._name in SUPPORTED_ACCELERATOR_LIST
