"""Accelerator abstraction.

Counterpart of the reference's `accelerator/abstract_accelerator.py:10`
(`DeepSpeedAccelerator` ABC, ~70 methods over torch device APIs). The JAX
programming model removes the need for explicit streams/events (dispatch is
async by default and ordering is data-flow driven), so those appear here as
no-op/barrier semantics; memory stats map to `Device.memory_stats()`.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional


class DeepSpeedAccelerator(abc.ABC):
    def __init__(self):
        self._name: Optional[str] = None
        self._communication_backend_name: Optional[str] = None

    # ---- identity ----
    @abc.abstractmethod
    def is_synchronized_device(self) -> bool: ...

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    # ---- devices ----
    @abc.abstractmethod
    def devices(self) -> List[Any]: ...

    def device_count(self) -> int:
        return len(self.devices())

    def current_device(self):
        return self.devices()[0]

    def current_device_name(self) -> str:
        return self.device_name(0)

    @abc.abstractmethod
    def local_device_count(self) -> int: ...

    # ---- async dispatch / "streams" ----
    def synchronize(self, device_index: Optional[int] = None) -> None:
        import jax
        jax.effects_barrier()

    def default_stream(self):  # streams are implicit under XLA
        return None

    def stream(self, _stream=None):
        import contextlib
        return contextlib.nullcontext()

    # ---- RNG: functional jax.random keys, seeded per host ----
    def manual_seed(self, seed: int):
        import jax
        return jax.random.PRNGKey(seed)

    def initial_seed(self) -> int:
        return 0

    # ---- memory ----
    def memory_stats(self, device_index: Optional[int] = None) -> Dict[str, int]:
        dev = self.devices()[device_index or 0]
        try:
            return dict(dev.memory_stats() or {})
        except Exception:
            return {}

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return self.memory_stats(device_index).get("peak_bytes_in_use", 0)

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index: Optional[int] = None) -> int:
        stats = self.memory_stats(device_index)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    def empty_cache(self) -> None:
        pass

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        pass

    # ---- dtype support ----
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    @abc.abstractmethod
    def communication_backend_name(self) -> str: ...

    # ---- profiler range markers (nvtx analog → jax named scopes) ----
    def range_push(self, msg: str):
        import jax.profiler
        tc = jax.profiler.TraceAnnotation(msg)
        tc.__enter__()
        self._ranges = getattr(self, "_ranges", [])
        self._ranges.append(tc)

    def range_pop(self):
        ranges = getattr(self, "_ranges", [])
        if ranges:
            ranges.pop().__exit__(None, None, None)

    # ---- events: XLA ordering is data-flow driven; events are barriers ----
    class _Event:
        def record(self, stream=None):
            pass

        def synchronize(self):
            import jax
            jax.effects_barrier()

        def query(self) -> bool:
            return True

        def elapsed_time(self, other) -> float:
            return 0.0

    def Event(self, enable_timing: bool = False):
        return self._Event()

    def Stream(self, *args, **kwargs):
        return None

    def current_stream(self, device_index=None):
        return None

    def set_device(self, device_index: int) -> None:
        pass  # SPMD: placement comes from shardings, not a current device

    def device(self, device_index=None):
        import contextlib
        return contextlib.nullcontext()

    # ---- host memory ----
    def pin_memory(self, array, align_bytes: int = 1):
        """Place on pinned host memory (reference pin_memory → CUDA pinned)."""
        import jax
        from jax.sharding import SingleDeviceSharding
        dev = self.devices()[0]
        try:
            # reference-API helper, not a residency path: callers that keep
            # the pinned array (swapper staging) register it themselves
            sh = SingleDeviceSharding(  # tpulint: disable=accounted-placement-routing
                dev, memory_kind="pinned_host")
            return jax.device_put(array, sh)
        except Exception:
            return array

    def is_pinned(self, array) -> bool:
        return getattr(getattr(array, "sharding", None), "memory_kind", None) \
            == "pinned_host"

    # ---- dtype / feature support ----
    def supported_dtypes(self):
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    def is_triton_supported(self) -> bool:
        return False  # Pallas fills this role on TPU

    def use_host_timers(self) -> bool:
        return True

    def resolves_data_dependency(self) -> bool:
        return True  # XLA schedules by data flow

    def handles_memory_backpressure(self) -> bool:
        return False

    def random(self):
        import jax
        return jax.random

    def lazy_call(self, callback):
        callback()

    def communication_backend_version(self) -> str:
        import jax
        return jax.__version__

    # ---- op builder lookup ----
    def get_op_builder(self, op_name: str):
        from deepspeed_tpu.op_builder import get_op_builder
        return get_op_builder(op_name)

    def create_op_builder(self, op_name: str):
        return self.get_op_builder(op_name)

    def on_accelerator(self, arr) -> bool:
        try:
            return any(d in self.devices() for d in arr.devices())
        except Exception:
            return False

    # ---- peak TFLOPs for MFU accounting (per chip, dense bf16) ----
    def peak_tflops(self, dtype: str = "bfloat16") -> float:
        return 0.0

    # ---- peak HBM bandwidth (GB/s) — the ledger's roofline denominator ----
    def peak_hbm_gbps(self) -> float:
        return 0.0
