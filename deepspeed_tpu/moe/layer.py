"""MoE layer + experts.

Counterpart of reference `deepspeed/moe/layer.py:17` (`MoE` — creates EP
groups at `:89`), `moe/experts.py` (`Experts`) and the `TopKGate` module.
EP "group creation" here is the `expert` mesh axis (utils/groups.py); expert
weights carry the 'expert' logical axis on dim 0 and are therefore sharded
across expert-parallel ranks, with ZeRO sharding them only over 'data'
(see ZeroShardingPlan.zero_axes — the expert-data-parallel split of
reference groups.py:117,188).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.moe.sharded_moe import (
    _gating_core, dispatch_combine, dispatch_combine_gmm,
    dispatch_combine_ragged, topkgating)
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


def _unpartitioned_mesh() -> bool:
    """True when every mesh axis is trivial (or no topology exists yet) —
    the regime where the bare megablox grouped GEMM is safe: GSPMD cannot
    partition a Pallas call, so on a real mesh it would silently all-gather
    its operands; `auto` keeps those on the ragged buffer path."""
    import jax
    from deepspeed_tpu.utils import groups
    try:
        topo = groups.get_topology(create_default=False)
    except RuntimeError:
        # no topology: only trust a literally-single-device process — a
        # user jitting over their own Mesh without groups.initialize must
        # land on the partitionable path
        return len(jax.devices()) == 1
    return topo.world_size == 1


def _gmm_mesh(num_experts: int):
    """Where (and how) the grouped GEMM may run under the installed
    topology. Returns:

      (None, 1)    — every axis trivial: bare single-shard megablox.
      (mesh, ep)   — pure expert-parallel mesh with num_experts % ep == 0:
                     the shard_map EP wrapper (sharded_grouped_gemm), each
                     shard running gmm with its group_offset.
      (None, 0)    — partitioned but unsupported (mixed axes, indivisible
                     experts, or no jax.shard_map): callers fall back to
                     ragged / bare gmm and say so via kernel_fallback.
    """
    if _unpartitioned_mesh():
        return None, 1
    from deepspeed_tpu.ops.pallas.sharded import serving_mesh
    mesh, ep = serving_mesh("expert")
    if mesh is not None and ep > 1 and num_experts % ep == 0:
        return mesh, ep
    return None, 0


def is_moe_param_path(path) -> bool:
    """expert_param_fn for the engine: params under an 'experts' collection."""
    return any(getattr(p, "key", getattr(p, "name", None)) == "experts"
               for p in path)


class Experts(nn.Module):
    """Batched expert FFNs (E, ...) — reference moe/experts.py, computed as a
    single grouped matmul over the expert-sharded leading axis (the Pallas/
    megablocks grouped-GEMM slot; XLA batches it on the MXU)."""
    num_experts: int
    hidden_size: int
    intermediate_size: int
    dtype: Any = jnp.bfloat16
    activation: str = "silu"  # silu → gated (mixtral-style); gelu → plain

    @nn.compact
    def __call__(self, x, group_sizes=None):
        """Batched form: x (E, C, D) → (E, C, D). Grouped form (when
        `group_sizes` is given): x (M, D) rows sorted by expert, each
        expert's span through its FFN as megablox grouped GEMMs — same
        params, no (E, C) padding."""
        e, d, f = self.num_experts, self.hidden_size, self.intermediate_size
        init = nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                            ("expert", "embed", "mlp"))
        init_out = nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                                ("expert", "mlp_in", "embed"))
        w_up = self.param("up", init, (e, d, f), jnp.float32).astype(self.dtype)
        w_down = self.param("down", init_out, (e, f, d), jnp.float32).astype(self.dtype)
        w_gate = (self.param("gate", init, (e, d, f), jnp.float32)
                  .astype(self.dtype) if self.activation == "silu" else None)
        if group_sizes is not None:
            from jax.ad_checkpoint import checkpoint_name
            from deepspeed_tpu.ops.pallas.grouped_gemm import (
                grouped_gemm, sharded_grouped_gemm)
            from deepspeed_tpu.ops.pallas.sharded import kernel_fallback
            mesh, ep = _gmm_mesh(e)
            if ep == 0:
                # forced/auto gmm on a mesh the EP wrapper can't cover:
                # the bare call still computes (GSPMD gathers operands) —
                # never silently
                kernel_fallback(
                    "grouped_gemm",
                    f"partitioned mesh is not pure expert-parallel with "
                    f"{e} % ep == 0; running unsharded (operands gathered)")

            def gg(lhs, rhs):
                # named so remat policies can SAVE grouped-GEMM outputs:
                # a Pallas call is not a dot, so plain checkpoint_dots
                # recomputes the whole grouped FFN in backward
                # (remat_policy='checkpoint_dots_gmm' in models/llama.py)
                out = (sharded_grouped_gemm(lhs, rhs, group_sizes, mesh)
                       if mesh is not None
                       else grouped_gemm(lhs, rhs, group_sizes))
                return checkpoint_name(out, "moe_gmm")
            if self.activation == "silu":
                h = nn.silu(gg(x, w_gate)) * gg(x, w_up)
            else:
                h = nn.gelu(gg(x, w_up))
            return gg(h, w_down)
        if self.activation == "silu":
            h = nn.silu(jnp.einsum("ecd,edf->ecf", x, w_gate)) * \
                jnp.einsum("ecd,edf->ecf", x, w_up)
        else:
            h = nn.gelu(jnp.einsum("ecd,edf->ecf", x, w_up))
        return jnp.einsum("ecf,efd->ecd", h, w_down)


class TopKGate(nn.Module):
    """Reference sharded_moe.py:TopKGate:449."""
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 8
    drop_tokens: bool = True
    noisy_gate_policy: Optional[str] = None
    # False = qwen2-moe style: top-k weights stay raw softmax probabilities
    # (HF norm_topk_prob); True = mixtral/reference renormalize-over-kept
    norm_topk_prob: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True, noise_rng=None, ragged: bool = False):
        wg = self.param("wg", nn.with_logical_partitioning(
            nn.initializers.normal(0.02), ("embed", None)),
            (x.shape[-1], self.num_experts), jnp.float32)
        logits = (x.astype(jnp.float32) @ wg)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        policy = self.noisy_gate_policy if train else None
        if ragged:
            l_aux, gate_k, topk_idx, pos_k, kept, _, cap = _gating_core(
                logits, self.k, cf, self.min_capacity, self.drop_tokens,
                noise_rng, policy, self.norm_topk_prob)
            return l_aux, gate_k, topk_idx, pos_k, kept, cap
        return topkgating(logits, self.k, cf, self.min_capacity,
                          self.drop_tokens, noise_rng, policy,
                          self.norm_topk_prob)


class MoE(nn.Module):
    """Drop-in MoE FFN block — reference deepspeed/moe/layer.py:MoE.

    Input (B, S, D) → (B, S, D); also returns (l_aux, exp_counts-like None)
    via the `aux_loss` flax variable collection (summed by the engine loss
    when present).
    """
    hidden_size: int
    num_experts: int = 1
    ep_size: int = 1                      # schema parity; actual EP = mesh axis
    k: int = 1
    intermediate_size: Optional[int] = None
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    drop_tokens: bool = True
    noisy_gate_policy: Optional[str] = None
    norm_topk_prob: bool = True
    use_residual: bool = False            # PR-MoE (residual expert)
    dtype: Any = jnp.bfloat16
    activation: str = "silu"
    # 'auto' (default): 'gmm' on an unpartitioned mesh, else 'ragged'.
    # 'gmm': expert-sorted rows through the megablox grouped GEMM — no
    # (E, C) buffer, but a Pallas call GSPMD cannot shard. 'ragged':
    # scatter/gather into the (E, C, D) buffer, O(T·k·D) movement, fully
    # GSPMD-partitionable (the EP path). 'einsum': the dense one-hot
    # formulation, O(T·E·C·D) — kept as the golden reference.
    dispatch_impl: str = "auto"

    @nn.compact
    def __call__(self, hidden_states, train: bool = True):
        b, s, d = hidden_states.shape
        f = self.intermediate_size or 4 * d
        x = hidden_states.reshape(b * s, d)
        x = shard_along(x, BATCH_AXES, None)

        gate = TopKGate(self.num_experts, self.k, self.capacity_factor,
                        self.eval_capacity_factor, self.min_capacity,
                        self.drop_tokens, self.noisy_gate_policy,
                        self.norm_topk_prob, self.dtype, name="gate")
        noise_rng = self.make_rng("gating") if self.has_rng("gating") else None

        experts = Experts(self.num_experts, d, f, self.dtype,
                          self.activation, name="experts")
        impl = self.dispatch_impl
        if impl == "auto":
            # r5 on-chip A/B (benchmarks/moe_breakdown.py): gmm wins the
            # fwd-only layer 1.2x (2.79 vs 3.35 ms), but its bwd kernels
            # (transpose_rhs gmm + tgmm) lose the train step 1.03-1.04x
            # even with the named-save remat policy — so auto picks gmm
            # only for inference, and only where the kernel can actually
            # run sharded: off-mesh, or a pure expert-parallel mesh via
            # the shard_map EP wrapper (r7; _gmm_mesh). Tiny row counts
            # (single-token decode) stay on ragged: the grouped kernel
            # was validated on-chip at large m only, and sub-tile m just
            # pads to the Mosaic minimum for no win.
            want_gmm = not train and b * s * self.k >= 1024
            gmm_ok = want_gmm and _gmm_mesh(self.num_experts)[1] > 0
            if want_gmm and not gmm_ok:
                from deepspeed_tpu.ops.pallas.sharded import kernel_fallback
                kernel_fallback(
                    "grouped_gemm",
                    "auto would pick gmm but the mesh is not trivial or "
                    "pure expert-parallel — using ragged dispatch")
            impl = "gmm" if gmm_ok else "ragged"
        assignments = float(b * s * self.k)
        if impl == "gmm":
            l_aux, gate_k, topk_idx, pos_k, kept, cap = gate(
                x, train, noise_rng, ragged=True)
            out = dispatch_combine_gmm(x, gate_k, topk_idx,
                                       self.num_experts, experts)
        elif impl == "ragged":
            l_aux, gate_k, topk_idx, pos_k, kept, cap = gate(
                x, train, noise_rng, ragged=True)
            out = dispatch_combine_ragged(x, gate_k, topk_idx, pos_k, kept,
                                          cap, self.num_experts, experts)
        else:
            l_aux, combine, dispatch, _ = gate(x, train, noise_rng)
            out = dispatch_combine(x, combine, dispatch, experts)
        if impl in ("gmm", "ragged"):
            # router telemetry (pre-capacity): fraction of the T·k expert
            # assignments routed to each expert (sums to 1), and the
            # fraction dropped by the capacity limit
            router_load = jnp.sum(
                jax.nn.one_hot(topk_idx, self.num_experts,
                               dtype=jnp.float32), axis=(0, 1)) / assignments
            router_drop = 1.0 - jnp.sum(
                kept.astype(jnp.float32)) / assignments
        else:
            # einsum path exposes only the post-capacity dispatch mask, so
            # its load is post-drop (sums to 1 - drop)
            d32 = dispatch.astype(jnp.float32)
            router_load = jnp.sum(d32, axis=(0, 2)) / assignments
            router_drop = 1.0 - jnp.sum(d32) / assignments
        # a no-op unless the caller made the 'metrics' collection mutable
        # (the zoo loss fns do); reduce keeps plain arrays so nn.scan
        # stacks a clean (L, E)/(L,) per model
        self.sow("metrics", "router_load", router_load,
                 init_fn=lambda: jnp.zeros((self.num_experts,), jnp.float32),
                 reduce_fn=lambda a, b_: a + b_)
        self.sow("metrics", "router_drop", router_drop,
                 init_fn=lambda: jnp.zeros([], jnp.float32),
                 reduce_fn=lambda a, b_: a + b_)

        if self.use_residual:
            # PR-MoE: add a dense residual MLP, gated per-token (layer.py residual path)
            res = Experts(1, d, f, self.dtype, self.activation, name="residual_expert")(
                x[None].reshape(1, b * s, d))[0]
            coef = nn.Dense(2, dtype=self.dtype, name="coefficient")(x)
            coef = jax.nn.softmax(coef.astype(jnp.float32), axis=-1).astype(out.dtype)
            out = out * coef[:, :1] + res * coef[:, 1:]

        self.sow("aux_loss", "moe_l_aux", l_aux,
                 init_fn=lambda: jnp.zeros([], jnp.float32),
                 reduce_fn=lambda a, b_: a + b_)
        return out.reshape(b, s, d)
