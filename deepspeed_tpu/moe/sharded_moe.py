"""MoE gating + expert-parallel dispatch.

Counterpart of the reference's `deepspeed/moe/sharded_moe.py` (`MOELayer:533`,
`TopKGate:449`, `top1gating:183`, `top2gating:290`, `topkgating:374`,
`_AllToAll:96`). Same semantics: softmax gate, top-k expert choice with a
capacity limit, load-balancing aux loss, dispatch/combine via one-hot einsums.

TPU mapping: the explicit `all_to_all` between the dispatch einsum and the
expert FFN becomes a sharding transition — token-major tensors are sharded
over ('data','expert') on the token dim, expert-major tensors over 'expert'
on the expert dim — and XLA inserts the all-to-all over the expert axis
(`_AllToAll:96`'s role). Everything is static-shape (capacity) and jit-safe.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.partitioning import shard_along


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int, k: int = 1) -> int:
    cap = int(num_tokens * k / num_experts * capacity_factor)
    cap = max(cap, min_capacity)
    # round up to a lane-friendly multiple
    return min(-(-cap // 8) * 8, num_tokens)


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def _gating_core(logits: jnp.ndarray, k: int, capacity_factor: float,
                 min_capacity: int, drop_tokens: bool,
                 noise_rng, noisy_gate_policy, norm_topk_prob: bool = True):
    """Shared top-k decisions. Returns (l_aux, gate_k (T,k), topk_idx (T,k),
    pos_k (T,k), kept (T,k), masks (T,k,E), cap). Both the einsum and the
    ragged dispatch consume exactly these decisions."""
    t, e = logits.shape
    cap = _capacity(t, e, capacity_factor, min_capacity, k)
    if not drop_tokens:
        cap = t  # every token can fit
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    select_from = logits
    if noisy_gate_policy == "RSample" and noise_rng is not None:
        select_from = logits + jax.random.gumbel(noise_rng, logits.shape)

    # top-k expert ids per token
    _, topk_idx = jax.lax.top_k(select_from, k)          # (T, k)
    masks = _one_hot(topk_idx, e)                        # (T, k, E)

    # load-balancing aux loss from the top-1 assignment (reference l_aux)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(masks[:, 0, :], axis=0)
    l_aux = jnp.sum(me * ce) * e

    # position of each token within its expert's capacity, ordered by k-slot
    # then token index (reference cumsum over the flattened (k*T, E) mask).
    flat = masks.transpose(1, 0, 2).reshape(k * t, e)    # k-major like reference
    pos_flat = jnp.cumsum(flat, axis=0) - flat           # (k*T, E)
    pos = pos_flat.reshape(k, t, e).transpose(1, 0, 2)   # (T, k, E)
    within_cap = pos < cap
    masks = masks * within_cap.astype(masks.dtype)

    # combine weights: gate prob per selected expert, renormalized over kept
    gate_k = jnp.take_along_axis(gates, topk_idx, axis=-1)       # (T, k)
    kept = jnp.sum(masks, axis=-1)                               # (T, k) 0/1
    gate_k = gate_k * kept
    if norm_topk_prob:
        denom = jnp.sum(gate_k, axis=-1, keepdims=True)
        gate_k = gate_k / jnp.maximum(denom, 1e-9)

    pos_k = jnp.sum(pos * masks, axis=-1).astype(jnp.int32)      # (T, k)
    return l_aux, gate_k, topk_idx, pos_k, kept, masks, cap


def topkgating(logits: jnp.ndarray,
               k: int,
               capacity_factor: float = 1.0,
               min_capacity: int = 8,
               drop_tokens: bool = True,
               noise_rng: Optional[jax.Array] = None,
               noisy_gate_policy: Optional[str] = None,
               norm_topk_prob: bool = True
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Generalized top-k gating (reference topkgating:374; top1/top2 are k=1,2).

    logits: (T, E). Returns (l_aux, combine_weights (T,E,C), dispatch_mask
    (T,E,C) bool, capacity C). O(T·E·C) outputs — prefer `topkgating_ragged`
    at scale."""
    l_aux, gate_k, topk_idx, pos_k, kept, masks, cap = _gating_core(
        logits, k, capacity_factor, min_capacity, drop_tokens, noise_rng,
        noisy_gate_policy, norm_topk_prob)
    loc = _one_hot(pos_k, cap)                                   # (T, k, C)
    combine = jnp.einsum("tk,tke,tkc->tec", gate_k, masks, loc)  # (T, E, C)
    dispatch = combine > 0
    return l_aux, combine, dispatch, cap


def topkgating_ragged(logits: jnp.ndarray,
                      k: int,
                      capacity_factor: float = 1.0,
                      min_capacity: int = 8,
                      drop_tokens: bool = True,
                      noise_rng: Optional[jax.Array] = None,
                      noisy_gate_policy: Optional[str] = None,
                      norm_topk_prob: bool = True):
    """Index-form gating for the scatter/gather dispatch: O(T·k) outputs
    instead of O(T·E·C) masks (the role of the reference's tutel/v2
    `top_k_gating` + `moe_scatter` kernel pair). Identical decisions to
    `topkgating` by construction (shared `_gating_core`)."""
    l_aux, gate_k, topk_idx, pos_k, kept, _, cap = _gating_core(
        logits, k, capacity_factor, min_capacity, drop_tokens, noise_rng,
        noisy_gate_policy, norm_topk_prob)
    return l_aux, gate_k, topk_idx, pos_k, kept, cap


def top1gating(logits, capacity_factor=1.0, min_capacity=8, drop_tokens=True,
               noise_rng=None, noisy_gate_policy=None):
    """Reference top1gating:183."""
    return topkgating(logits, 1, capacity_factor, min_capacity, drop_tokens,
                      noise_rng, noisy_gate_policy)


def top2gating(logits, capacity_factor=1.0, min_capacity=8, drop_tokens=True,
               noise_rng=None):
    """Reference top2gating:290."""
    return topkgating(logits, 2, capacity_factor, min_capacity, drop_tokens, noise_rng)


def dispatch_combine(x: jnp.ndarray,
                     combine: jnp.ndarray,
                     dispatch: jnp.ndarray,
                     expert_fn,
                     ) -> jnp.ndarray:
    """Dispatch tokens to experts, apply expert_fn, combine back.

    x: (T, D) token-major (sharded over tokens on ('data','expert')).
    expert_fn: (E, C, D) -> (E, C, D) expert-major (sharded over 'expert').
    Mirrors MOELayer.forward:586 einsum→a2a→expert→a2a→combine.
    """
    expert_inputs = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    # sharding transition = the all-to-all over the expert axis
    expert_inputs = shard_along(expert_inputs, "expert", None, None)
    expert_outputs = expert_fn(expert_inputs)
    expert_outputs = shard_along(expert_outputs, "expert", None, None)
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_outputs)
    return out


def dispatch_combine_gmm(x: jnp.ndarray, gate_k: jnp.ndarray,
                         topk_idx: jnp.ndarray, num_experts: int,
                         grouped_fn) -> jnp.ndarray:
    """Sorted-rows dispatch for the grouped expert GEMM: the role of the
    reference's `cutlass_ops/moe_gemm` + `ragged_ops/moe_{scatter,gather}`
    kernel trio in ONE data layout. Tokens are stable-sorted by expert id
    (T·k rows, no (E, capacity) padding), `grouped_fn(rows, group_sizes)`
    runs the expert FFN as megablox grouped GEMMs, and the combine gathers
    back to token order weighted by the gate.

    Capacity-dropped slots are compute-included but WEIGHT-zeroed (gate_k
    is already masked by `kept` in `_gating_core`) — numerically identical
    to the buffer paths, and still fewer FLOPs than the (E, C) buffer
    whenever capacity_factor > 1. Sharding: megablox is a Pallas call
    GSPMD cannot partition, but pure expert-parallel meshes ride the
    shard_map EP wrapper (`ops/pallas/grouped_gemm.sharded_grouped_gemm`,
    per-shard `group_offset` + masked psum — `Experts` picks it via
    `_gmm_mesh`); any OTHER nontrivial mesh still routes to
    `dispatch_combine_ragged` from `MoE`'s auto rule.
    """
    t, d = x.shape
    k = topk_idx.shape[1]
    flat_e = topk_idx.reshape(-1)                       # (T·k,)
    order = jnp.argsort(flat_e)                         # stable: token-order
    xs = jnp.take(x, order // k, axis=0)                # within each expert
    group_sizes = jnp.bincount(flat_e, length=num_experts)
    out_s = grouped_fn(xs, group_sizes)                 # (T·k, D)
    out_k = jnp.take(out_s, jnp.argsort(order), axis=0).reshape(t, k, d)
    return jnp.einsum("tk,tkd->td", gate_k.astype(x.dtype), out_k)


def dispatch_combine_ragged(x: jnp.ndarray, gate_k: jnp.ndarray,
                            topk_idx: jnp.ndarray, pos_k: jnp.ndarray,
                            kept: jnp.ndarray, cap: int, num_experts: int,
                            expert_fn) -> jnp.ndarray:
    """Scatter/gather dispatch: O(T·k·D) data movement, no (T,E,C) tensor.

    The counterpart of the reference's ragged MoE kernels
    (`inference/v2/kernels/ragged_ops/{moe_scatter,moe_gather}`,
    `cutlass_ops/moe_gemm` grouped GEMM): tokens scatter into the (E, C, D)
    expert buffer at slot `expert·C + pos` (dropped tokens fall out of
    bounds), experts run as one batched matmul, and the combine is a gather
    back to token order weighted by the gate. Sharding transitions on the
    expert buffer are the all-to-all over the `expert` mesh axis.
    """
    t, d = x.shape
    k = topk_idx.shape[1]
    dest = topk_idx * cap + pos_k                              # (T, k)
    dest = jnp.where(kept > 0, dest, num_experts * cap)        # dropped → OOB
    xk = jnp.broadcast_to(x[:, None], (t, k, d)).reshape(t * k, d)
    buf = jnp.zeros((num_experts * cap, d), x.dtype)
    # each (expert, slot) receives at most one token → add ≡ set, OOB dropped
    buf = buf.at[dest.reshape(-1)].add(xk, mode="drop")
    expert_inputs = buf.reshape(num_experts, cap, d)
    expert_inputs = shard_along(expert_inputs, "expert", None, None)
    expert_outputs = expert_fn(expert_inputs)
    expert_outputs = shard_along(expert_outputs, "expert", None, None)
    flat = expert_outputs.reshape(num_experts * cap, d)
    out_k = jnp.take(flat, dest, axis=0, mode="fill", fill_value=0)  # (T, k, D)
    return jnp.einsum("tk,tkd->td", gate_k.astype(x.dtype), out_k)
