from deepspeed_tpu.moe.layer import MoE, Experts, TopKGate, is_moe_param_path
from deepspeed_tpu.moe.sharded_moe import top1gating, top2gating, topkgating
