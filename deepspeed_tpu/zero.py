"""`deepspeed.zero` API surface (reference `deepspeed/runtime/zero/
partition_parameters.py:816` `Init`, `:2112` `GatheredParameters`).

The reference patches `nn.Module.__init__` so parameters are partitioned the
moment they are constructed (host RAM never holds the full model). The JAX
equivalent needs no patching: `Init.materialize` runs the flax initializer
under `jax.jit` with ZeRO-3 `out_shardings`, so every parameter is *created
directly into its shard* — no rank ever materializes the full tensor.
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.partition import ZeroShardingPlan
from deepspeed_tpu.utils import groups


class Init:
    """ZeRO-3 partitioned construction.

        with deepspeed_tpu.zero.Init(config_dict_or_path=ds_config) as zi:
            model, params, specs = zi.materialize(MyModel(cfg), sample_input)

    The context-manager form is API parity; all the work happens in
    `materialize` (declarative — nothing to patch)."""

    def __init__(self, module: Any = None, data_parallel_group: Any = None,
                 mem_efficient_linear: bool = True, remote_device: Any = None,
                 pin_memory: bool = False, config_dict_or_path: Any = None,
                 config: Any = None, enabled: bool = True, dtype: Any = None,
                 mpu: Any = None, param_swapper: Any = None):
        import json
        raw = config_dict_or_path if config_dict_or_path is not None else config
        if isinstance(raw, str):
            with open(raw) as f:
                raw = json.load(f)
        zero_raw = (raw or {}).get("zero_optimization", {"stage": 3})
        self.zero_config = DeepSpeedZeroConfig(**zero_raw)
        if self.zero_config.stage != 3:
            self.zero_config.stage = 3  # Init implies stage 3 (reference assert)
        self.enabled = enabled
        self.dtype = dtype

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def materialize(self, model: Any, *init_args, rng: Any = None,
                    rngs: Any = None):
        """(model, params, base_specs): parameters initialized shard-by-shard
        into the ZeRO-3 placement of the installed topology."""
        from deepspeed_tpu.utils.partitioning import extract_params_and_specs
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        topo = groups.get_topology()
        plan = ZeroShardingPlan(topo, self.zero_config)

        init_rngs = rngs if rngs is not None else rng
        abstract = jax.eval_shape(model.init, init_rngs, *init_args)
        shapes, base_specs = extract_params_and_specs(abstract)
        if not self.enabled:
            def plain_init(r):
                variables = model.init(r, *init_args)
                raw, _ = extract_params_and_specs(variables)
                return raw
            with topo.mesh:
                raw = jax.jit(plain_init)(init_rngs)
            return model, raw, base_specs
        param_specs = plan.tree_specs(shapes, base_specs, "param")
        shardings = plan.tree_shardings(param_specs, "param")

        def init_fn(r):
            variables = model.init(r, *init_args)
            raw, _ = extract_params_and_specs(variables)
            return raw

        with topo.mesh:
            params = jax.jit(init_fn, out_shardings=shardings)(init_rngs)
        return model, params, base_specs


@contextlib.contextmanager
def GatheredParameters(params, modifier_rank: Optional[int] = None,
                       fwd_module: Any = None, enabled: bool = True):
    """Reference `GatheredParameters:2112` — full (replicated) values of
    ZeRO-3-sharded params inside the context. Read-only use: consume the
    yielded tree; to modify, mutate the yielded list-wrapper's `.data`."""
    if not enabled:
        yield params
        return
    topo = groups.get_topology()
    mesh = topo.mesh

    def gather(x):
        if not hasattr(x, "sharding"):
            return x
        return jax.device_put(x, NamedSharding(mesh, P()))

    yield jax.tree_util.tree_map(gather, params)
