"""Structural compression: pruning that REMOVES structures, not just masks.

Counterpart of the reference's dim-reduction helpers
(`/root/reference/deepspeed/compression/basic_layer.py:212`
`fix_row_col_pruning_helper(dim_reduction=True)`, `:254`
`fix_head_pruning_helper`, `:492` `fix_channel_pruning_helper`) and the
layer-reduction student initialization
(`/root/reference/deepspeed/compression/compress.py:192`).

TPU-first design: the zoo stacks transformer blocks on a leading layer axis
(nn.scan), so structural pruning is a *tree-slicing* transform — one shared
mask across the stack (stacked params must stay rectangular), applied by
gathering the kept indices on the head / intermediate axes. Layer reduction
is literally `leaf[teacher_layer]` on the stacked axis. Both return a new
(config, params) pair describing a genuinely smaller model; nothing is
masked at runtime.

Pruning sites are chosen so removal is EXACT (bit-equal modulo float
reassociation) to masking:
- attention heads: score/remove on o_proj's input rows — a head whose
  o-contribution is zero contributes nothing, so dropping its q/k/v/o
  slices preserves the layer output. GQA: whole KV groups (1 kv head +
  n_rep query heads) are removed together so the grouped layout survives.
- MLP rows: score/remove on down_proj's input rows — dropping an
  intermediate unit with a zeroed down-row is exact.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _get(tree: Dict, *path):
    node = tree
    for k in path:
        if not isinstance(node, dict) or k not in node:
            return None
        node = node[k]
    return node


def _split_root(tree: Dict) -> Tuple[Dict, bool]:
    """Accept both the flax variables dict ({'params': {...}}) and the
    engine's bare inner tree; return (inner, was_wrapped)."""
    if isinstance(tree, dict) and "params" in tree and "layers" not in tree:
        return tree["params"], True
    return tree, False


def _join_root(inner: Dict, wrapped: bool, orig: Dict) -> Dict:
    if not wrapped:
        return inner
    out = dict(orig)
    out["params"] = inner
    return out


def _leaf_val(x):
    return x.value if hasattr(x, "value") else x


def _with_val(orig, new):
    """Preserve flax Partitioned metadata boxes when replacing a leaf."""
    if hasattr(orig, "value"):
        return orig.replace_boxed(new) if hasattr(orig, "replace_boxed") else \
            dataclasses.replace(orig, value=new)
    return new


def head_group_scores(params: Dict, num_kv_heads: int) -> jnp.ndarray:
    """Liveness score per KV group, summed over the layer stack (shared
    mask — see module docstring). A head is dead if EITHER its o_proj input
    rows OR its v_proj output columns were zeroed (training-time masks may
    sit at either site), so the score is the elementwise MIN of the two
    groups' L1 masses. Returns (num_kv_heads,)."""
    inner, _ = _split_root(params)
    o = _leaf_val(_get(inner, "layers", "self_attn", "o_proj", "kernel"))
    if o is None:
        raise ValueError("head pruning needs a llama-tree param layout "
                         "(params/layers/self_attn/o_proj)")
    L, hin, d = o.shape
    per_group = hin // num_kv_heads
    score = jnp.sum(jnp.abs(o).reshape(L, num_kv_heads, per_group, d),
                    axis=(0, 2, 3))
    v = _leaf_val(_get(inner, "layers", "self_attn", "v_proj", "kernel"))
    if v is not None:
        vg = v.shape[-1] // num_kv_heads
        v_score = jnp.sum(
            jnp.abs(v).reshape(L, -1, num_kv_heads, vg), axis=(0, 1, 3))
        scale = jnp.maximum(jnp.mean(score), 1e-12) / \
            jnp.maximum(jnp.mean(v_score), 1e-12)
        score = jnp.minimum(score, v_score * scale)
    return score


def mlp_row_scores(params: Dict) -> jnp.ndarray:
    """Liveness score per intermediate unit, summed over the layer stack.
    An FFN unit is dead if ANY of its down_proj input row, up_proj output
    column, or gate_proj output column was zeroed (silu(0)=0 kills the
    gated product), so the score is the elementwise MIN of the per-site L1
    masses — structural removal then agrees with a training-time mask
    applied at any of the three sites. Returns (intermediate_size,)."""
    inner, _ = _split_root(params)
    dn = _leaf_val(_get(inner, "layers", "mlp", "down_proj", "kernel"))
    if dn is None:
        raise ValueError("row pruning needs a llama-tree param layout "
                         "(params/layers/mlp/down_proj)")
    score = jnp.sum(jnp.abs(dn), axis=(0, 2))
    mean = jnp.maximum(jnp.mean(score), 1e-12)
    for name in ("up_proj", "gate_proj"):
        k = _leaf_val(_get(inner, "layers", "mlp", name, "kernel"))
        if k is None:
            continue
        s = jnp.sum(jnp.abs(k), axis=(0, 1))
        s = s * (mean / jnp.maximum(jnp.mean(s), 1e-12))
        score = jnp.minimum(score, s)
    return score


def _topk_keep(scores: jnp.ndarray, dense_ratio: float,
               align: int = 1, what: str = "structures") -> jnp.ndarray:
    """Sorted indices of the kept (highest-score) structures. `align` rounds
    the keep-count up to a multiple (pass 8/128 to stay MXU-tileable).

    Warns loudly when a REMOVED structure is still live (score above ~0):
    then removal is lossy, not mask-exact — e.g. a query-head-granular
    training mask that keeps one live head in each KV group, while group
    removal must drop whole groups."""
    n = scores.shape[0]
    k = max(1, int(round(n * dense_ratio)))
    if align > 1:
        k = min(n, -(-k // align) * align)
    order = jnp.argsort(scores)[::-1]
    if k < n:
        removed_max = float(scores[order[k]])
        live_thresh = 1e-6 * max(float(scores[order[0]]), 1e-12)
        if removed_max > live_thresh:
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                "structural pruning removes LIVE %s (max removed score "
                "%.3g vs top %.3g) — the shrunk model will NOT match the "
                "masked model; check that training masks align with the "
                "removable granularity (KV groups / FFN rows)",
                what, removed_max, float(scores[order[0]]))
    idx = order[:k]
    return jnp.sort(idx)


def slice_layers(params: Dict, layer_indices: Sequence[int]) -> Dict:
    """Select layers from the stacked axis: every leaf under `layers`
    becomes `leaf[layer_indices]`. The shared mechanism behind layer
    reduction (`redundancy_clean`) and `student_initialization`."""
    idx = jnp.asarray(list(layer_indices), jnp.int32)
    inner, wrapped = _split_root(params)
    if _get(inner, "layers") is None:
        raise ValueError("slice_layers needs a stacked 'layers' subtree")
    new_layers = jax.tree_util.tree_map(
        lambda t: _with_val(t, jnp.take(_leaf_val(t), idx, axis=0)),
        inner["layers"])
    new_inner = dict(inner)
    new_inner["layers"] = new_layers
    return _join_root(new_inner, wrapped, params)


def head_mask_from_keep(keep_groups: jnp.ndarray, num_kv_heads: int,
                        hin: int) -> jnp.ndarray:
    """(hin,) 0/1 mask over o_proj input rows for the masked-parity form."""
    per_group = hin // num_kv_heads
    m = jnp.zeros((num_kv_heads,), jnp.float32).at[keep_groups].set(1.0)
    return jnp.repeat(m, per_group)


def prune_attention_heads(config: Any, params: Dict, dense_ratio: float,
                          align: int = 1) -> Tuple[Any, Dict]:
    """Remove whole KV groups (GQA-safe), returning (new_config, new_params)
    with `num_attention_heads`/`num_key_value_heads` shrunk. Exact w.r.t.
    the o-masked model."""
    n_q = config.num_attention_heads
    n_kv = getattr(config, "num_key_value_heads", None) or n_q
    n_rep = n_q // n_kv
    keep = _topk_keep(head_group_scores(params, n_kv), dense_ratio, align,
                      what="KV head groups")
    k = int(keep.shape[0])

    inner, wrapped = _split_root(params)
    attn = _get(inner, "layers", "self_attn")
    hd_q = _leaf_val(attn["q_proj"]["kernel"]).shape[-1] // n_q

    def slice_heads(leaf, n_heads, axis: int):
        """Gather kept KV groups on `axis` (grouped as n_heads blocks —
        q/o use n_kv blocks of n_rep·hd so group removal stays GQA-consistent)."""
        v = _leaf_val(leaf)
        per = v.shape[axis] // n_heads
        shape = v.shape[:axis] + (n_heads, per) + v.shape[axis + 1:]
        g = v.reshape(shape)
        g = jnp.take(g, keep, axis=axis)
        out_shape = v.shape[:axis] + (keep.shape[0] * per,) + v.shape[axis + 1:]
        return _with_val(leaf, g.reshape(out_shape))

    new_attn = dict(attn)
    for name in ("q_proj", "k_proj", "v_proj"):
        mod = dict(new_attn[name])
        mod["kernel"] = slice_heads(mod["kernel"], n_kv, 2)
        if "bias" in mod:
            mod["bias"] = slice_heads(mod["bias"], n_kv, 1)
        new_attn[name] = mod
    o_mod = dict(new_attn["o_proj"])
    o_mod["kernel"] = slice_heads(o_mod["kernel"], n_kv, 1)
    new_attn["o_proj"] = o_mod

    layers = dict(_get(inner, "layers"))
    layers["self_attn"] = new_attn
    new_inner = dict(inner)
    new_inner["layers"] = layers
    p = _join_root(new_inner, wrapped, params)

    new_cfg = config
    if dataclasses.is_dataclass(config):
        kw = dict(num_attention_heads=k * n_rep, num_key_value_heads=k)
        if any(f.name == "head_dim_override"
               for f in dataclasses.fields(config)):
            kw["head_dim_override"] = hd_q
        elif getattr(config, "hidden_size", 0) // (k * n_rep) != hd_q:
            raise ValueError(
                f"{type(config).__name__} derives head_dim from "
                f"hidden_size//num_attention_heads and has no "
                f"head_dim_override field — after pruning to {k * n_rep} "
                f"heads it would compute "
                f"{getattr(config, 'hidden_size', 0) // (k * n_rep)} "
                f"instead of the preserved width {hd_q}; add the override "
                f"field to the config (see LlamaConfig)")
        new_cfg = dataclasses.replace(config, **kw)
    return new_cfg, p


def prune_mlp_rows(config: Any, params: Dict, dense_ratio: float,
                   align: int = 1) -> Tuple[Any, Dict]:
    """Remove intermediate (FFN) units, shrinking gate/up output columns and
    down input rows. Exact w.r.t. the down-row-masked model."""
    keep = _topk_keep(mlp_row_scores(params), dense_ratio, align,
                      what="FFN rows")
    inner, wrapped = _split_root(params)
    mlp = dict(_get(inner, "layers", "mlp"))
    for name, axis in (("gate_proj", 2), ("up_proj", 2), ("down_proj", 1)):
        if name not in mlp:
            continue
        mod = dict(mlp[name])
        mod["kernel"] = _with_val(
            mod["kernel"], jnp.take(_leaf_val(mod["kernel"]), keep, axis=axis))
        if "bias" in mod and axis == 2:
            mod["bias"] = _with_val(
                mod["bias"], jnp.take(_leaf_val(mod["bias"]), keep, axis=1))
        mlp[name] = mod
    layers = dict(_get(inner, "layers"))
    layers["mlp"] = mlp
    new_inner = dict(inner)
    new_inner["layers"] = layers
    p = _join_root(new_inner, wrapped, params)
    new_cfg = dataclasses.replace(
        config, intermediate_size=int(keep.shape[0])) \
        if dataclasses.is_dataclass(config) else config
    return new_cfg, p


def shrink_model(config: Any, params: Dict,
                 head_dense_ratio: Optional[float] = None,
                 row_dense_ratio: Optional[float] = None,
                 align: int = 1) -> Tuple[Any, Dict]:
    """One-call structural prune: heads then MLP rows. The returned config
    builds a smaller model whose forward matches the masked original."""
    if head_dense_ratio is not None:
        config, params = prune_attention_heads(config, params,
                                               head_dense_ratio, align)
    if row_dense_ratio is not None:
        config, params = prune_mlp_rows(config, params, row_dense_ratio,
                                        align)
    return config, params


def student_initialization(student_params: Dict, teacher_params: Dict,
                           teacher_layer: Sequence[int],
                           other_module_name: Optional[Sequence[str]] = None
                           ) -> Dict:
    """Reference `student_initialization` (`compress.py:192`): initialize a
    shallower student from selected teacher layers.

    On the stacked layout this is a slice of the layer axis:
    `student.layers[i] = teacher.layers[teacher_layer[i]]` for every leaf
    under `params/layers`. `other_module_name` selects which non-layer
    top-level modules to copy (default: all that exist in both trees —
    embeddings, final norm, lm_head)."""
    s_inner, s_wrapped = _split_root(student_params)
    t_inner, _ = _split_root(teacher_params)
    s_layers = _get(s_inner, "layers")
    if s_layers is None or _get(t_inner, "layers") is None:
        raise ValueError("student_initialization needs stacked 'layers' "
                         "subtrees in both param trees")

    n_student = jax.tree_util.tree_leaves(s_layers)[0].shape[0]
    if n_student != len(teacher_layer):
        raise ValueError(
            f"teacher_layer selects {len(teacher_layer)} layers but the "
            f"student has {n_student}")

    new_inner = dict(s_inner)
    new_inner["layers"] = _split_root(
        slice_layers(teacher_params, teacher_layer))[0]["layers"]
    names = other_module_name if other_module_name is not None else \
        [k for k in new_inner if k != "layers" and k in t_inner]
    for name in names:
        if name not in t_inner:
            raise KeyError(f"teacher has no module '{name}'")
        new_inner[name] = t_inner[name]
    return _join_root(new_inner, s_wrapped, student_params)
