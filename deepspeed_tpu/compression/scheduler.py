"""Compression scheduler (reference `compression/scheduler.py`): enables
each compression method when its `schedule_offset` step is reached."""

from __future__ import annotations

from typing import Dict


class CompressionScheduler:
    def __init__(self, compression_config: Dict):
        self.config = compression_config or {}
        self.training_steps = 0
        self.enabled: Dict[str, bool] = {}

    def step(self, step_zero_check: bool = False):
        self.training_steps += 1
        for method, block in self.config.items():
            shared = (block or {}).get("shared_parameters", {})
            offset = int(shared.get("schedule_offset", 0))
            if shared.get("enabled", False):
                self.enabled[method] = self.training_steps >= offset

    def is_enabled(self, method: str) -> bool:
        return self.enabled.get(method, False)
