"""Compression entry points (reference `compression/compress.py:100`
`init_compression`, `:148 redundancy_clean`).

The reference walks the module tree and swaps layers for compressed
variants. Here compression compiles to a parameter transform applied inside
the loss (QAT fake-quant / prune masks via `compress_params`) — configured
by the same `compression_training` JSON block."""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.basic_layer import (
    _topk_unit_mask, channel_prune_mask, magnitude_prune_mask,
    row_prune_mask, ste_binarize, ste_quantize, ste_ternarize)
from deepspeed_tpu.utils.logging import logger, warn_once


def _matches(path_str: str, patterns) -> bool:
    def one(p):
        if fnmatch.fnmatch(path_str, p):
            return True
        try:
            return re.search(p, path_str) is not None
        except re.error:   # glob-only patterns ('*up_proj*') aren't regexes
            return False
    return any(one(p) for p in patterns)


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "name", p))) for p in path)


def _enabled_groups(block: Dict, technique: str):
    """Yield (params_dict, modules) for each enabled different_group of a
    technique (reference `compression/config.py` group schema). Technique-
    wide knobs living in shared_parameters (e.g. head_pruning's num_heads,
    reference `config.py:371`) are merged in as a base with group-level
    override."""
    tech = (block or {}).get(technique, {})
    shared = tech.get("shared_parameters", {})
    if not shared.get("enabled", False):
        return
    base = {k: v for k, v in shared.items() if k != "enabled"}
    for name, group in (tech.get("different_groups", {}) or {}).items():
        yield {**base, **group.get("params", {})}, group.get("modules", ["*"])


def build_compress_fn(compression_config: Dict,
                      structural_guard: bool = False) -> Callable:
    """compression_training JSON block → params→params transform.

    Supported techniques (same JSON keys as reference
    `compression/config.py` / `constants.py`): weight_quantization,
    sparse_pruning, row_pruning (structured output-unit masks),
    head_pruning (grouped masks on the attention-output matrix's head
    axis, `num_heads` from the group params), channel_pruning (conv HWIO
    output channels), activation_quantization (recorded on the returned
    fn as `.activation_bits` — activations are quantized by the layer,
    not a param transform). Each group has `params` and `modules` glob
    patterns. Technique order matches reference `redundancy_clean`'s
    order_list (`compress.py:169`): quantize applied LAST so pruning
    masks see unquantized magnitudes."""
    block = compression_config or {}
    wq_groups = [(int(p.get("target_bits", 8)), m)
                 for p, m in _enabled_groups(block, "weight_quantization")]
    sp_groups = [(1.0 - float(p.get("dense_ratio", 0.5)), m)
                 for p, m in _enabled_groups(block, "sparse_pruning")]
    rp_groups = [(1.0 - float(p.get("dense_ratio", 0.5)), m)
                 for p, m in _enabled_groups(block, "row_pruning")]
    hp_groups = []
    for p, m in _enabled_groups(block, "head_pruning"):
        if "num_heads" not in p:
            # reference asserts this too (`compression/config.py:371`) —
            # a silent default would disable pruning with no indication
            raise ValueError(
                "head_pruning needs num_heads (under shared_parameters, "
                "reference schema, or the group's params)")
        hp_groups.append((1.0 - float(p.get("dense_ratio", 0.5)),
                          int(p["num_heads"]), m))
    cp_groups = [(1.0 - float(p.get("dense_ratio", 0.5)), m)
                 for p, m in _enabled_groups(block, "channel_pruning")]
    aq = [int(p.get("bits", 8))
          for p, _ in _enabled_groups(block, "activation_quantization")]

    def compress_params(params):
        def per_leaf(path, w):
            if not (hasattr(w, "ndim") and w.ndim >= 2
                    and jnp.issubdtype(w.dtype, jnp.floating)):
                return w
            ps = _path_str(path)
            for ratio, mods in sp_groups:
                if _matches(ps, mods):
                    mask = jax.lax.stop_gradient(magnitude_prune_mask(w, ratio))
                    w = w * mask
            # Structured masks apply to KERNELS only: a stacked bias is
            # (L, F) — rank-by-own-magnitude there would pick a different
            # kept set than the kernel (breaking removal parity), and a
            # head mask on its axis 0 would zero whole LAYERS.
            is_kernel = ps.endswith("kernel") or ps.endswith("kernel/value")
            for ratio, mods in rp_groups:
                if is_kernel and _matches(ps, mods):
                    if structural_guard and "down_proj" in ps:
                        # row_prune_mask zeroes OUTPUT columns — on the
                        # down projection that is the HIDDEN axis, i.e.
                        # residual-stream pruning, which structural FFN-row
                        # removal cannot express. Point row_pruning at the
                        # gate/up projections instead.
                        warn_once(
                            ("structural_rp_skip", ps),
                            "structural redundancy_clean: row_pruning "
                            "matched %s — skipping (its output axis is the "
                            "hidden dim, not FFN rows; target gate/up "
                            "projections for structural row pruning)", ps)
                        continue
                    w = w * jax.lax.stop_gradient(row_prune_mask(w, ratio))
            for ratio, num_heads, mods in hp_groups:
                if is_kernel and _matches(ps, mods):
                    w = w * jax.lax.stop_gradient(
                        _head_axis_mask(w, num_heads, ratio))
            for ratio, mods in cp_groups:
                if _matches(ps, mods) and w.ndim == 4:
                    w = w * jax.lax.stop_gradient(channel_prune_mask(w, ratio))
            for bits, mods in wq_groups:
                if _matches(ps, mods):
                    if bits == 1:
                        w = ste_binarize(w)
                    elif bits == 2:
                        w = ste_ternarize(w)
                    else:
                        w = ste_quantize(w, bits)
            return w
        return jax.tree_util.tree_map_with_path(per_leaf, params)

    compress_params.activation_bits = aq[0] if aq else None
    return compress_params


def _head_axis_mask(w: jnp.ndarray, num_heads: int, ratio: float):
    """Head mask for an attention OUTPUT matrix (reference head pruning
    targets `attention.output.dense` ONLY, `basic_layer.py:254` — point the
    group's `modules` at the o/output projection, not '*self_attn*': a
    q/k/v kernel's (L, D, H*hd) layout would put the mask on the embed
    axis, which this function cannot distinguish by shape): the INPUT axis
    (rows of our (H*hd, D) kernels; the stacked form is (L, H*hd, D)) is
    grouped into `num_heads` blocks ranked by L1 mass."""
    axis = w.ndim - 2
    h = w.shape[axis]
    if h % num_heads:
        # same config-error class as a missing num_heads (reference asserts
        # here, `helper.py` head pruning): a warn-and-skip silently
        # disables pruning for the kernel
        raise ValueError(
            f"head_pruning: matched kernel axis {axis} (size {h}) is not "
            f"divisible by num_heads={num_heads} — check the group's "
            "modules pattern and num_heads")
    hd = h // num_heads
    grouped = jnp.moveaxis(w, axis, 0).reshape(num_heads, hd, -1)
    mass = jnp.sum(jnp.abs(grouped), axis=(1, 2))
    keep = max(1, int(round(num_heads * (1.0 - ratio))))
    head_mask = jnp.repeat(_topk_unit_mask(mass, keep, w.dtype), hd)
    shape = [1] * w.ndim
    shape[axis] = h
    return head_mask.reshape(shape)


def init_compression(model: Any = None, deepspeed_config: Any = None,
                     teacher_model: Any = None, mpu: Any = None) -> Callable:
    """Reference `init_compression:100` — returns the compression transform
    to wrap a loss_fn with:

        compress = init_compression(deepspeed_config=cfg)
        loss_fn = lambda p, b, r: base_loss(compress(p), b, r)
    """
    fn = build_compress_fn(_load_cfg(deepspeed_config))
    logger.info("compression initialized (QAT fake-quant / prune transform)")
    return fn


def _load_cfg(cfg):
    import json
    if isinstance(cfg, str):
        with open(cfg) as f:
            cfg = json.load(f)
    return (cfg or {}).get("compression_training", {})


def redundancy_clean(model_or_params: Any, deepspeed_config: Any = None,
                     mpu: Any = None):
    """Reference `redundancy_clean:148` — remove the model's redundancy for
    deployment.

    Two forms, mirroring the reference's mask-vs-dim_reduction split
    (`fix_compression` is called with dim_reduction=True when a group has
    `related_modules`):

    - params tree in → masks/quantization baked into the weights (no STE).
    - `(model_config, params)` tuple in (zoo llama-tree models) → STRUCTURAL
      removal: head_pruning / row_pruning groups physically shrink the
      attention-head and FFN-intermediate axes (via
      `compression.structured.shrink_model`) and a `layer_reduction` block
      drops layers from the stacked axis; returns the smaller
      `(new_config, new_params)`. Remaining techniques are then baked as
      masks."""
    block = _load_cfg(deepspeed_config)

    if isinstance(model_or_params, tuple) and len(model_or_params) == 2:
        # Reference order (`fix_compression` then dim_reduction): BAKE the
        # pruning masks into the weights first — training-time masks only
        # exist inside the loss (STE leaves raw params nonzero at masked
        # positions), so structural scoring must run on masked weights to
        # recover the trained kept-set exactly. Quantization bakes LAST so
        # its global scale sees the same surviving weights as the masked
        # model (removal doesn't change max|w| → identical quant grid).
        from deepspeed_tpu.compression import structured
        config, params = model_or_params
        n_kv = getattr(config, "num_key_value_heads", None) or \
            getattr(config, "num_attention_heads", None)
        for p, _ in _enabled_groups(block, "head_pruning"):
            if n_kv and int(p.get("num_heads", n_kv)) != n_kv:
                warn_once(
                    ("structural_hp_groups", p.get("num_heads"), n_kv),
                    "structural redundancy_clean: head_pruning group uses "
                    "num_heads=%s but removal is KV-group granular "
                    "(num_key_value_heads=%d) — a query-granular training "
                    "mask whose kept heads straddle groups cannot be "
                    "removed exactly", p.get("num_heads"), n_kv)
        fn_prune = build_compress_fn({k: v for k, v in block.items()
                                      if k != "weight_quantization"},
                                     structural_guard=True)
        params = jax.lax.stop_gradient(fn_prune(params))
        fn = build_compress_fn({k: v for k, v in block.items()
                                if k == "weight_quantization"})
        lr = block.get("layer_reduction", {})
        if lr.get("enabled", False):
            import dataclasses
            teacher_layer = list(lr.get("teacher_layer", []))
            params = structured.slice_layers(params, teacher_layer)
            if dataclasses.is_dataclass(config):
                config = dataclasses.replace(
                    config, num_hidden_layers=len(teacher_layer))
        # The structural shrink uses ONE shared mask per site (stacked
        # layers must stay rectangular), so per-group module scoping
        # collapses: the first enabled group's ratio wins.
        hp = [float(p.get("dense_ratio", 0.5))
              for p, _ in _enabled_groups(block, "head_pruning")]
        rp = [float(p.get("dense_ratio", 0.5))
              for p, _ in _enabled_groups(block, "row_pruning")]
        if len(hp) > 1 or len(rp) > 1:
            logger.warning(
                "structural redundancy_clean: multiple pruning groups "
                "collapse to one shared mask; using the first group's ratio")
        head_ratio = hp[0] if hp else None
        row_ratio = rp[0] if rp else None
        config, params = structured.shrink_model(
            config, params, head_dense_ratio=head_ratio,
            row_dense_ratio=row_ratio)
        return config, jax.lax.stop_gradient(fn(params))

    fn = build_compress_fn(block)
    return jax.lax.stop_gradient(fn(model_or_params))
