"""Compression entry points (reference `compression/compress.py:100`
`init_compression`, `:148 redundancy_clean`).

The reference walks the module tree and swaps layers for compressed
variants. Here compression compiles to a parameter transform applied inside
the loss (QAT fake-quant / prune masks via `compress_params`) — configured
by the same `compression_training` JSON block."""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.basic_layer import (
    magnitude_prune_mask, ste_binarize, ste_quantize, ste_ternarize)
from deepspeed_tpu.utils.logging import logger


def _matches(path_str: str, patterns) -> bool:
    return any(fnmatch.fnmatch(path_str, p) or re.search(p, path_str)
               for p in patterns)


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "name", p))) for p in path)


def build_compress_fn(compression_config: Dict) -> Callable:
    """compression_training JSON block → params→params transform.

    Supported (same keys as reference `compression/config.py`):
    weight_quantization.{shared_parameters,different_groups...}, and
    sparse_pruning. Each group has `params` (target bits / ratio) and
    `modules` glob patterns."""
    wq = (compression_config or {}).get("weight_quantization", {})
    sp = (compression_config or {}).get("sparse_pruning", {})

    wq_groups = []
    if wq.get("shared_parameters", {}).get("enabled", False):
        for name, group in (wq.get("different_groups", {}) or {}).items():
            bits = int(group.get("params", {}).get("target_bits", 8))
            mods = group.get("modules", ["*"])
            wq_groups.append((bits, mods))
    sp_groups = []
    if sp.get("shared_parameters", {}).get("enabled", False):
        for name, group in (sp.get("different_groups", {}) or {}).items():
            ratio = float(group.get("params", {}).get("dense_ratio", 0.5))
            mods = group.get("modules", ["*"])
            sp_groups.append((1.0 - ratio, mods))  # dense_ratio → prune ratio

    def compress_params(params):
        def per_leaf(path, w):
            if not (hasattr(w, "ndim") and w.ndim >= 2
                    and jnp.issubdtype(w.dtype, jnp.floating)):
                return w
            ps = _path_str(path)
            for ratio, mods in sp_groups:
                if _matches(ps, mods):
                    mask = jax.lax.stop_gradient(magnitude_prune_mask(w, ratio))
                    w = w * mask
            for bits, mods in wq_groups:
                if _matches(ps, mods):
                    if bits == 1:
                        w = ste_binarize(w)
                    elif bits == 2:
                        w = ste_ternarize(w)
                    else:
                        w = ste_quantize(w, bits)
            return w
        return jax.tree_util.tree_map_with_path(per_leaf, params)

    return compress_params


def init_compression(model: Any = None, deepspeed_config: Any = None,
                     teacher_model: Any = None, mpu: Any = None) -> Callable:
    """Reference `init_compression:100` — returns the compression transform
    to wrap a loss_fn with:

        compress = init_compression(deepspeed_config=cfg)
        loss_fn = lambda p, b, r: base_loss(compress(p), b, r)
    """
    import json
    cfg = deepspeed_config
    if isinstance(cfg, str):
        with open(cfg) as f:
            cfg = json.load(f)
    block = (cfg or {}).get("compression_training", {})
    fn = build_compress_fn(block)
    logger.info("compression initialized (QAT fake-quant / prune transform)")
    return fn


def redundancy_clean(model_or_params: Any, deepspeed_config: Any = None,
                     mpu: Any = None):
    """Reference `redundancy_clean:148` — bake the compression into the
    weights (quantize/prune for real, no STE) for deployment."""
    import json
    cfg = deepspeed_config
    if isinstance(cfg, str):
        with open(cfg) as f:
            cfg = json.load(f)
    fn = build_compress_fn((cfg or {}).get("compression_training", {}))
    return jax.lax.stop_gradient(fn(model_or_params))
