from deepspeed_tpu.compression.compress import (  # noqa: F401
    init_compression, redundancy_clean)
from deepspeed_tpu.compression.basic_layer import (  # noqa: F401
    PrunedLinear, QuantizedConv, QuantizedEmbedding, QuantizedLinear,
    activation_quantize, knowledge_distillation_loss)
from deepspeed_tpu.compression.scheduler import CompressionScheduler  # noqa: F401
