from deepspeed_tpu.compression.compress import (  # noqa: F401
    init_compression, redundancy_clean)
from deepspeed_tpu.compression.basic_layer import (  # noqa: F401
    ColumnParallelQuantizedLinear, CompressedBatchNorm, PrunedLinear,
    QuantizedConv, QuantizedEmbedding, QuantizedLinear,
    RowParallelQuantizedLinear, activation_quantize, channel_prune_mask,
    knowledge_distillation_loss, row_prune_mask, shrink_conv_bn)
from deepspeed_tpu.compression.structured import (  # noqa: F401
    prune_attention_heads, prune_mlp_rows, shrink_model,
    student_initialization)
from deepspeed_tpu.compression.scheduler import CompressionScheduler  # noqa: F401
