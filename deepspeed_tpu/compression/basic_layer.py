"""Compression layers (reference `compression/basic_layer.py:65-830`:
quantized/pruned Linear/Embedding variants).

TPU-first: compression is a *parameter transform*, not a module swap — the
layers here exist for users building compressed models directly, while
`compress.init_compression` applies the same transforms to an existing param
tree (the `module_replacement` analog without module surgery). Fake-quant
uses straight-through estimation (gradients flow unquantized), matching the
reference's QAT formulation.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def ste_quantize(w: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Symmetric fake-quant with straight-through gradients
    (reference Quantizer/BinaryQuantizer/TernaryQuantizer family)."""
    levels = 2.0 ** (bits - 1) - 1
    amax = jnp.max(jnp.abs(w)) + 1e-12
    scale = amax / levels
    q = jnp.clip(jnp.round(w / scale), -levels, levels) * scale
    return w + jax.lax.stop_gradient(q - w)


def ste_binarize(w: jnp.ndarray) -> jnp.ndarray:
    """1-bit (BinaryQuantizer): sign * mean|w|, STE."""
    q = jnp.sign(w) * jnp.mean(jnp.abs(w))
    return w + jax.lax.stop_gradient(q - w)


def ste_ternarize(w: jnp.ndarray) -> jnp.ndarray:
    """2-bit ternary (TernaryQuantizer): threshold at 0.7·mean|w|."""
    thre = 0.7 * jnp.mean(jnp.abs(w))
    mask = (jnp.abs(w) > thre).astype(w.dtype)
    alpha = jnp.sum(jnp.abs(w) * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    q = jnp.sign(w) * mask * alpha
    return w + jax.lax.stop_gradient(q - w)


def magnitude_prune_mask(w: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """Keep the top-(1-ratio) weights by |magnitude| (SparsePruner dense)."""
    k = max(1, int(round(w.size * (1.0 - ratio))))
    thresh = jnp.sort(jnp.abs(w).reshape(-1))[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def head_prune_mask(w: jnp.ndarray, num_heads: int, ratio: float) -> jnp.ndarray:
    """Structured attention-head pruning (HeadPruner): rank heads by the L1
    mass of their output columns; w: (D, H*hd)."""
    d, hhd = w.shape
    hd = hhd // num_heads
    mass = jnp.sum(jnp.abs(w).reshape(d, num_heads, hd), axis=(0, 2))
    keep = max(1, int(round(num_heads * (1.0 - ratio))))
    thresh = jnp.sort(mass)[-keep]
    head_mask = (mass >= thresh).astype(w.dtype)
    return jnp.broadcast_to(head_mask[None, :, None], (d, num_heads, hd)
                            ).reshape(d, hhd)


class QuantizedLinear(nn.Module):
    """Reference `LinearLayer_Compress` with weight quantization enabled."""
    features: int
    bits: int = 8
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("kernel", nn.initializers.normal(0.02),
                       (x.shape[-1], self.features), jnp.float32)
        if self.bits == 1:
            wq = ste_binarize(w)
        elif self.bits == 2:
            wq = ste_ternarize(w)
        else:
            wq = ste_quantize(w, self.bits)
        out = x @ wq.astype(self.dtype)
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros_init(),
                           (self.features,), jnp.float32)
            out = out + b.astype(self.dtype)
        return out


class PrunedLinear(nn.Module):
    """Reference `LinearLayer_Compress` with sparse pruning enabled; the
    mask is recomputed from current magnitudes (dynamic) each call."""
    features: int
    ratio: float = 0.5
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("kernel", nn.initializers.normal(0.02),
                       (x.shape[-1], self.features), jnp.float32)
        mask = jax.lax.stop_gradient(magnitude_prune_mask(w, self.ratio))
        out = x @ (w * mask).astype(self.dtype)
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros_init(),
                           (self.features,), jnp.float32)
            out = out + b.astype(self.dtype)
        return out


class QuantizedEmbedding(nn.Module):
    """Reference `Embedding_Compress` (`compression/basic_layer.py:440`):
    embedding table trained through STE weight quantization."""
    num_embeddings: int
    features: int
    bits: int = 8
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, ids):
        w = self.param("embedding", nn.initializers.normal(0.02),
                       (self.num_embeddings, self.features), jnp.float32)
        wq = ste_binarize(w) if self.bits == 1 else ste_quantize(w, self.bits)
        return jnp.take(wq.astype(self.dtype), ids, axis=0)


class QuantizedConv(nn.Module):
    """Reference `Conv2dLayer_Compress`: 2D convolution with STE-quantized
    kernel (NHWC)."""
    features: int
    kernel_size: tuple = (3, 3)
    bits: int = 8
    strides: tuple = (1, 1)
    padding: str = "SAME"
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kshape = (*self.kernel_size, x.shape[-1], self.features)
        w = self.param("kernel", nn.initializers.normal(0.02), kshape,
                       jnp.float32)
        wq = ste_binarize(w) if self.bits == 1 else ste_quantize(w, self.bits)
        out = jax.lax.conv_general_dilated(
            x.astype(self.dtype), wq.astype(self.dtype), self.strides,
            self.padding, dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros_init(),
                           (self.features,), jnp.float32)
            out = out + b.astype(self.dtype)
        return out


def activation_quantize(x: jnp.ndarray, bits: int = 8,
                        method: str = "symmetric") -> jnp.ndarray:
    """Reference activation quantization (QuantAct): fake-quantize
    activations with a straight-through estimator. 'symmetric' scales by
    max|x|; 'asymmetric' min/max affine."""
    if method == "symmetric":
        scale = jnp.max(jnp.abs(x)) / (2 ** (bits - 1) - 1)
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.round(x / scale) * scale
    else:
        lo, hi = jnp.min(x), jnp.max(x)
        span = jnp.where(hi - lo == 0, 1.0, hi - lo)
        n = 2 ** bits - 1
        q = jnp.round((x - lo) / span * n) / n * span + lo
    return x + jax.lax.stop_gradient(q - x)


def knowledge_distillation_loss(student_logits: jnp.ndarray,
                                teacher_logits: jnp.ndarray,
                                temperature: float = 1.0) -> jnp.ndarray:
    """Reference `compression/scheduler.py` distillation term: temperature-
    scaled KL(teacher || student) over the vocabulary, mean over tokens.
    Combine as `loss + alpha * kd_loss` per the staged-KD schedule."""
    t = temperature
    sp = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t, axis=-1)
    tp = jax.nn.softmax(teacher_logits.astype(jnp.float32) / t, axis=-1)
    kl = jnp.sum(tp * (jnp.log(jnp.clip(tp, 1e-20)) - sp), axis=-1)
    return jnp.mean(kl) * (t * t)
