"""Compression layers (reference `compression/basic_layer.py:65-830`:
quantized/pruned Linear/Embedding variants).

TPU-first: compression is a *parameter transform*, not a module swap — the
layers here exist for users building compressed models directly, while
`compress.init_compression` applies the same transforms to an existing param
tree (the `module_replacement` analog without module surgery). Fake-quant
uses straight-through estimation (gradients flow unquantized), matching the
reference's QAT formulation.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def ste_quantize(w: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Symmetric fake-quant with straight-through gradients
    (reference Quantizer/BinaryQuantizer/TernaryQuantizer family)."""
    levels = 2.0 ** (bits - 1) - 1
    amax = jnp.max(jnp.abs(w)) + 1e-12
    scale = amax / levels
    q = jnp.clip(jnp.round(w / scale), -levels, levels) * scale
    return w + jax.lax.stop_gradient(q - w)


def ste_binarize(w: jnp.ndarray) -> jnp.ndarray:
    """1-bit (BinaryQuantizer): sign * mean|w|, STE."""
    q = jnp.sign(w) * jnp.mean(jnp.abs(w))
    return w + jax.lax.stop_gradient(q - w)


def ste_ternarize(w: jnp.ndarray) -> jnp.ndarray:
    """2-bit ternary (TernaryQuantizer): threshold at 0.7·mean|w|."""
    thre = 0.7 * jnp.mean(jnp.abs(w))
    mask = (jnp.abs(w) > thre).astype(w.dtype)
    alpha = jnp.sum(jnp.abs(w) * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    q = jnp.sign(w) * mask * alpha
    return w + jax.lax.stop_gradient(q - w)


def magnitude_prune_mask(w: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """Keep the top-(1-ratio) weights by |magnitude| (SparsePruner dense)."""
    k = max(1, int(round(w.size * (1.0 - ratio))))
    thresh = jnp.sort(jnp.abs(w).reshape(-1))[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def _topk_unit_mask(mass: jnp.ndarray, keep: int, dtype) -> jnp.ndarray:
    """1-D keep mask from the SAME descending argsort `structured.py`'s
    `_topk_keep` slices, so masked-vs-shrunk parity holds on tied scores
    (a `mass >= thresh` comparison keeps every tied unit and can exceed
    the keep-count — common with quantized or freshly-initialized
    weights)."""
    idx = jnp.argsort(mass)[::-1][:keep]
    return jnp.zeros(mass.shape, dtype).at[idx].set(1)


def row_prune_mask(w: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """Structured output-neuron pruning (reference
    `fix_row_col_pruning_helper`, `compression/basic_layer.py:212`): rank
    output units by the L1 mass of their weights and zero the bottom
    `ratio`. Kernels here are (in, out), so a reference "row" is our output
    COLUMN; the mask broadcasts as (1, out)."""
    mass = jnp.sum(jnp.abs(w), axis=tuple(range(w.ndim - 1)))
    keep = max(1, int(round(mass.shape[0] * (1.0 - ratio))))
    return _topk_unit_mask(mass, keep, w.dtype)[None, :]


def channel_prune_mask(w: jnp.ndarray, ratio: float) -> jnp.ndarray:
    """Structured conv output-channel pruning (reference
    `fix_channel_pruning_helper`, `compression/basic_layer.py:492`): w is
    HWIO; rank output channels by L1 mass over (H, W, I)."""
    mass = jnp.sum(jnp.abs(w), axis=(0, 1, 2))
    keep = max(1, int(round(mass.shape[0] * (1.0 - ratio))))
    return _topk_unit_mask(mass, keep, w.dtype)


def head_prune_mask(w: jnp.ndarray, num_heads: int, ratio: float) -> jnp.ndarray:
    """Structured attention-head pruning (HeadPruner): rank heads by the L1
    mass of their output columns; w: (D, H*hd)."""
    d, hhd = w.shape
    hd = hhd // num_heads
    mass = jnp.sum(jnp.abs(w).reshape(d, num_heads, hd), axis=(0, 2))
    keep = max(1, int(round(num_heads * (1.0 - ratio))))
    head_mask = _topk_unit_mask(mass, keep, w.dtype)
    return jnp.broadcast_to(head_mask[None, :, None], (d, num_heads, hd)
                            ).reshape(d, hhd)


class QuantizedLinear(nn.Module):
    """Reference `LinearLayer_Compress` with weight quantization enabled.

    `logical` (optional) attaches flax logical-axis names to the kernel —
    the declarative form of the reference's TP-variant compressed layers
    (see ColumnParallelQuantizedLinear below). `ratio` additionally applies
    structured output-unit (row) pruning before quantization."""
    features: int
    bits: int = 8
    ratio: Optional[float] = None
    use_bias: bool = True
    dtype: Any = jnp.float32
    logical: Optional[tuple] = None

    @nn.compact
    def __call__(self, x):
        kernel_init = nn.initializers.normal(0.02)
        bias_init = nn.initializers.zeros_init()
        if self.logical is not None:
            kernel_init = nn.with_logical_partitioning(kernel_init,
                                                       self.logical)
            bias_init = nn.with_logical_partitioning(bias_init,
                                                     (self.logical[-1],))
        w = self.param("kernel", kernel_init,
                       (x.shape[-1], self.features), jnp.float32)
        if self.ratio is not None:
            w = w * jax.lax.stop_gradient(row_prune_mask(w, self.ratio))
        if self.bits == 1:
            wq = ste_binarize(w)
        elif self.bits == 2:
            wq = ste_ternarize(w)
        else:
            wq = ste_quantize(w, self.bits)
        out = x @ wq.astype(self.dtype)
        if self.use_bias:
            b = self.param("bias", bias_init, (self.features,), jnp.float32)
            out = out + b.astype(self.dtype)
        return out


class PrunedLinear(nn.Module):
    """Reference `LinearLayer_Compress` with sparse pruning enabled; the
    mask is recomputed from current magnitudes (dynamic) each call."""
    features: int
    ratio: float = 0.5
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("kernel", nn.initializers.normal(0.02),
                       (x.shape[-1], self.features), jnp.float32)
        mask = jax.lax.stop_gradient(magnitude_prune_mask(w, self.ratio))
        out = x @ (w * mask).astype(self.dtype)
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros_init(),
                           (self.features,), jnp.float32)
            out = out + b.astype(self.dtype)
        return out


class QuantizedEmbedding(nn.Module):
    """Reference `Embedding_Compress` (`compression/basic_layer.py:440`):
    embedding table trained through STE weight quantization."""
    num_embeddings: int
    features: int
    bits: int = 8
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, ids):
        w = self.param("embedding", nn.initializers.normal(0.02),
                       (self.num_embeddings, self.features), jnp.float32)
        wq = ste_binarize(w) if self.bits == 1 else ste_quantize(w, self.bits)
        return jnp.take(wq.astype(self.dtype), ids, axis=0)


class QuantizedConv(nn.Module):
    """Reference `Conv2dLayer_Compress`: 2D convolution with STE-quantized
    kernel (NHWC)."""
    features: int
    kernel_size: tuple = (3, 3)
    bits: int = 8
    strides: tuple = (1, 1)
    padding: str = "SAME"
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kshape = (*self.kernel_size, x.shape[-1], self.features)
        w = self.param("kernel", nn.initializers.normal(0.02), kshape,
                       jnp.float32)
        wq = ste_binarize(w) if self.bits == 1 else ste_quantize(w, self.bits)
        out = jax.lax.conv_general_dilated(
            x.astype(self.dtype), wq.astype(self.dtype), self.strides,
            self.padding, dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            b = self.param("bias", nn.initializers.zeros_init(),
                           (self.features,), jnp.float32)
            out = out + b.astype(self.dtype)
        return out


def activation_quantize(x: jnp.ndarray, bits: int = 8,
                        method: str = "symmetric") -> jnp.ndarray:
    """Reference activation quantization (QuantAct): fake-quantize
    activations with a straight-through estimator. 'symmetric' scales by
    max|x|; 'asymmetric' min/max affine."""
    if method == "symmetric":
        scale = jnp.max(jnp.abs(x)) / (2 ** (bits - 1) - 1)
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.round(x / scale) * scale
    else:
        lo, hi = jnp.min(x), jnp.max(x)
        span = jnp.where(hi - lo == 0, 1.0, hi - lo)
        n = 2 ** bits - 1
        q = jnp.round((x - lo) / span * n) / n * span + lo
    return x + jax.lax.stop_gradient(q - x)


class CompressedBatchNorm(nn.Module):
    """Reference `BNLayer_Compress` (`compression/basic_layer.py:611`):
    BatchNorm2d that participates in channel pruning — `channel_mask`
    (from the upstream conv's `channel_prune_mask`) zeroes the scale/bias of
    pruned channels so the masked network matches the structurally shrunk
    one. NHWC; running stats via flax BatchNorm."""
    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, channel_mask: Optional[jnp.ndarray] = None):
        y = nn.BatchNorm(use_running_average=self.use_running_average,
                         momentum=self.momentum, epsilon=self.epsilon,
                         dtype=self.dtype, param_dtype=jnp.float32,
                         name="bn")(x)
        if channel_mask is not None:
            y = y * jax.lax.stop_gradient(channel_mask).astype(y.dtype)
        return y


def shrink_conv_bn(conv_kernel: jnp.ndarray, bn_params: dict,
                   keep: jnp.ndarray, next_conv_kernel=None):
    """Apply channel pruning FOR REAL (`fix_channel_pruning_helper` with
    dim_reduction): slice the conv's kept output channels, the BN
    scale/bias/stats, and the next conv's input channels. `keep` is the
    sorted kept-channel index vector."""
    new_conv = jnp.take(conv_kernel, keep, axis=-1)
    new_bn = {k: (jnp.take(v, keep, axis=-1) if hasattr(v, "ndim") and
                  v.ndim >= 1 and v.shape[-1] == conv_kernel.shape[-1] else v)
              for k, v in bn_params.items()}
    new_next = None if next_conv_kernel is None else \
        jnp.take(next_conv_kernel, keep, axis=2)
    return new_conv, new_bn, new_next


class ColumnParallelQuantizedLinear(QuantizedLinear):
    """Reference `ColumnParallelLinear_Compress`
    (`compression/basic_layer.py:767`). Declarative TP: the kernel's output
    axis carries the 'mlp' logical name (→ 'model' mesh axis), so GSPMD
    shards the columns across TP ranks; no explicit scatter/gather — the
    reference's `_CopyToModelParallelRegion` machinery is the partitioner's
    job. Quantization scales are global (XLA inserts the max-reduce across
    shards), matching the reference's single-scale semantics."""
    logical: Optional[tuple] = ("embed", "mlp")


class RowParallelQuantizedLinear(QuantizedLinear):
    """Reference `RowParallelLinear_Compress`
    (`compression/basic_layer.py:802`): input axis sharded over TP
    ('mlp' → 'model'); the partial-sum allreduce the reference issues by
    hand (`_ReduceFromModelParallelRegion`) is inserted by GSPMD when the
    sharded contraction meets the replicated output spec."""
    logical: Optional[tuple] = ("mlp", "embed")


def knowledge_distillation_loss(student_logits: jnp.ndarray,
                                teacher_logits: jnp.ndarray,
                                temperature: float = 1.0) -> jnp.ndarray:
    """Reference `compression/scheduler.py` distillation term: temperature-
    scaled KL(teacher || student) over the vocabulary, mean over tokens.
    Combine as `loss + alpha * kd_loss` per the staged-KD schedule."""
    t = temperature
    sp = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t, axis=-1)
    tp = jax.nn.softmax(teacher_logits.astype(jnp.float32) / t, axis=-1)
    kl = jnp.sum(tp * (jnp.log(jnp.clip(tp, 1e-20)) - sp), axis=-1)
    return jnp.mean(kl) * (t * t)
