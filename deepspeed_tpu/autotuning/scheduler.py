"""Autotuning experiment scheduler + tuners (reference
`autotuning/scheduler.py`, `autotuning/tuner/{base,index_based,model_based}`).

The reference schedules each experiment as a separate launcher job across
free resources, persists every experiment's `exp.json`/result, and resumes
interrupted sweeps. On TPU a trial is an in-process engine build + a few
compiled steps, so the scheduler here is sequential — but it keeps the
reference's durable contract:

- every experiment is assigned a stable id (hash of its config);
- results stream to `<results_dir>/experiments.jsonl` as they finish;
- a re-run SKIPS experiments already recorded (resumability);
- the final `best.json` holds the winning full engine config.

Tuners decide the ORDER (and early stop) of the candidate list:
- GridTuner: in-order exhaustive sweep (reference tuner/index_based grid);
- RandomTuner: shuffled order with an optional trial cap
  (tuner/index_based random);
- ModelBasedTuner: cost-model-guided — candidates are explored best-first
  by a prior throughput model seeded from the memory estimator, and the
  sweep early-stops after `patience` consecutive non-improvements
  (the role of the reference's XGBoost-based tuner/model_based, with an
  analytic prior instead of a learned one).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


def _exp_id(cand: Dict[str, Any]) -> str:
    return hashlib.sha1(
        json.dumps(cand, sort_keys=True, default=str).encode()).hexdigest()[:12]


class GridTuner:
    """Exhaustive in-order sweep."""

    def order(self, candidates, autotuner):
        return list(candidates)

    def should_stop(self, history) -> bool:
        return False


class RandomTuner:
    def __init__(self, max_trials: Optional[int] = None, seed: int = 0):
        self.max_trials = max_trials
        self.seed = seed

    def order(self, candidates, autotuner):
        out = list(candidates)
        random.Random(self.seed).shuffle(out)
        return out[:self.max_trials] if self.max_trials else out

    def should_stop(self, history) -> bool:
        return False


class ModelBasedTuner:
    """Prior-ordered search with early stop.

    The prior scores each candidate's expected throughput analytically:
    tokens in flight (mbs) push throughput up until memory pressure; ZeRO
    stage adds collective overhead at small dp. Ranking by the prior means
    the best configs run FIRST, so the patience-based early stop prunes
    the tail of the sweep — the reference's model-based tuner does the
    same with a learned cost model over flattened config features."""

    def __init__(self, patience: int = 5):
        # patience 5, not 3: the prior is coarse — e.g. it can't know that
        # matmul-saving remat beats whole-block remat by ~10% when both
        # fit (v5e ledger); too-eager stopping pruned exactly that winner
        # in the r4 flagship sweep
        self.patience = patience

    def _prior(self, cand, autotuner) -> float:
        mbs = cand["micro_batch_size"]
        stage = cand["zero_stage"]
        score = float(mbs)  # more tokens per step amortize fixed work
        # memory estimate as a soft penalty: candidates near the budget
        # tend to pay remat/fragmentation costs before they OOM
        if autotuner is not None and autotuner.num_params and \
                autotuner.max_memory_bytes:
            extra = {k: v for k, v in cand.items()
                     if k not in ("zero_stage", "micro_batch_size")}
            need = autotuner._estimate(stage, mbs, extra)
            frac = need / autotuner.max_memory_bytes
            score *= max(0.05, 1.25 - frac)
        # remat policies that save matmul outputs beat whole-block remat
        # when they fit (v5e ledger: 59.5% vs 54.1%)
        policy = cand.get("remat_policy")
        if policy in ("checkpoint_dots", "dots"):
            score *= 1.1
        elif policy == "host_offload":
            score *= 0.9
        return score

    def order(self, candidates, autotuner):
        return sorted(candidates,
                      key=lambda c: -self._prior(c, autotuner))

    def should_stop(self, history) -> bool:
        done = [h for h in history if h.get("samples_per_sec") is not None]
        if len(done) <= self.patience:
            return False
        best_i = max(range(len(done)),
                     key=lambda i: done[i]["samples_per_sec"])
        return len(done) - 1 - best_i >= self.patience


TUNERS = {"gridsearch": GridTuner, "random": RandomTuner,
          "model_based": ModelBasedTuner}


class ExperimentScheduler:
    """Run an Autotuner's candidate experiments durably (resumable,
    results persisted), in tuner order."""

    def __init__(self, autotuner, results_dir: str = "autotuning_results",
                 tuner: Any = None):
        self.autotuner = autotuner
        self.results_dir = os.path.abspath(results_dir)
        if isinstance(tuner, str):
            tuner = TUNERS[tuner]()
        self.tuner = tuner or ModelBasedTuner()
        os.makedirs(self.results_dir, exist_ok=True)
        self._log_path = os.path.join(self.results_dir, "experiments.jsonl")

    def _load_done(self) -> Dict[str, Dict]:
        done = {}
        if os.path.isfile(self._log_path):
            with open(self._log_path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rec = json.loads(line)
                        done[rec["exp_id"]] = rec
        return done

    def run(self) -> Dict:
        """Execute the sweep; returns the best full engine config (also
        written to best.json)."""
        at = self.autotuner
        candidates = self.tuner.order(at._candidates(), at)
        done = self._load_done()
        if done:
            logger.info(f"autotuning scheduler: resuming — "
                        f"{len(done)} experiments already recorded in "
                        f"{self._log_path}")
        history: List[Dict] = list(done.values())
        with open(self._log_path, "a") as log:
            for cand in candidates:
                eid = _exp_id(cand)
                if eid in done:
                    continue
                if self.tuner.should_stop(history):
                    logger.info("autotuning scheduler: early stop "
                                f"({type(self.tuner).__name__} patience)")
                    break
                tput = at._run_trial(cand)
                rec = {"exp_id": eid, **cand, "samples_per_sec": tput}
                history.append(rec)
                at.results.append(rec)
                log.write(json.dumps(rec) + "\n")
                log.flush()
                logger.info(f"autotuning scheduler: {rec}")

        ok = [h for h in history if h.get("samples_per_sec") is not None]
        if not ok:
            raise RuntimeError("autotuning: every experiment failed")
        from deepspeed_tpu.autotuning.autotuner import apply_candidate
        best = max(ok, key=lambda h: h["samples_per_sec"])
        out = apply_candidate(at.base_config, best)
        with open(os.path.join(self.results_dir, "best.json"), "w") as f:
            json.dump({"best_experiment": best, "config": out}, f, indent=2,
                      default=str)
        logger.info(f"autotuning scheduler: best = {best} "
                    f"(full sweep in {self._log_path})")
        return out
