"""Autotuner (reference `autotuning/autotuner.py:42`).

Same strategy as the reference: estimate ZeRO model-state memory to prune
the space (`:278`), then launch short real runs over (zero stage,
micro-batch) candidates and keep the fastest (`tune:404`). The reference
schedules each experiment as a separate launcher job; on TPU each trial is
an in-process engine build + a few compiled steps (cheap, no process
spawning), which also means the tuner composes with any mesh.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger

TUNING_MICRO_BATCH_SIZES = [1, 2, 4, 8]
TUNING_ZERO_STAGES = [0, 1, 2, 3]


def estimate_zero_memory(num_params: int, stage: int, dp_size: int,
                         bf16: bool = True) -> int:
    """Per-device model-state bytes (reference memory estimation `:278` /
    `zero/model_states_mem_needs`): params + grads + Adam(m, v, master)."""
    bytes_per = 2 if bf16 else 4
    p = num_params * bytes_per          # model params
    g = num_params * 4                  # fp32 grad accumulation
    o = num_params * 12 if bf16 else num_params * 8  # master + m + v
    if stage >= 3:
        p //= dp_size
    if stage >= 2:
        g //= dp_size
    if stage >= 1:
        o //= dp_size
    return p + g + o


class Autotuner:
    """Search (zero_stage, micro_batch) by short measured runs.

    build_engine(config_dict) -> engine; batch_fn(mbs) -> global batch.
    """

    def __init__(self, build_engine: Callable[[Dict], Any],
                 batch_fn: Callable[[int], Dict],
                 base_config: Dict,
                 micro_batch_sizes: Optional[List[int]] = None,
                 zero_stages: Optional[List[int]] = None,
                 num_steps: int = 3, warmup: int = 1,
                 max_memory_bytes: Optional[int] = None,
                 num_params: Optional[int] = None,
                 dp_size: int = 1):
        self.build_engine = build_engine
        self.batch_fn = batch_fn
        self.base_config = base_config
        self.micro_batch_sizes = micro_batch_sizes or TUNING_MICRO_BATCH_SIZES
        self.zero_stages = zero_stages or TUNING_ZERO_STAGES
        self.num_steps = num_steps
        self.warmup = warmup
        self.max_memory_bytes = max_memory_bytes
        self.num_params = num_params
        self.dp_size = dp_size
        self.results: List[Dict] = []

    def _candidates(self) -> List[Tuple[int, int]]:
        out = []
        for stage in self.zero_stages:
            if self.max_memory_bytes and self.num_params:
                need = estimate_zero_memory(self.num_params, stage, self.dp_size)
                if need > self.max_memory_bytes:
                    logger.info(f"autotuner: prune stage {stage} "
                                f"(needs {need/1e9:.1f} GB)")
                    continue
            for mbs in self.micro_batch_sizes:
                out.append((stage, mbs))
        return out

    def _run_trial(self, stage: int, mbs: int) -> Optional[float]:
        import jax
        cfg = dict(self.base_config)
        cfg["train_micro_batch_size_per_gpu"] = mbs
        cfg.setdefault("zero_optimization", {})
        cfg["zero_optimization"] = {**cfg["zero_optimization"], "stage": stage}
        try:
            engine = self.build_engine(cfg)
            batch = self.batch_fn(mbs)
            for _ in range(self.warmup):
                engine.train_batch(batch=batch)
            jax.block_until_ready(engine.state)
            t0 = time.perf_counter()
            for _ in range(self.num_steps):
                loss = engine.train_batch(batch=batch)
            jax.block_until_ready((engine.state, loss))
            dt = time.perf_counter() - t0
            samples_s = engine.train_batch_size() * self.num_steps / dt
            return samples_s
        except Exception as e:
            logger.info(f"autotuner: trial (stage={stage}, mbs={mbs}) failed: {e}")
            return None

    def tune(self) -> Dict:
        """Reference `tune:404` → best config dict (fastest samples/s)."""
        best = None
        for stage, mbs in self._candidates():
            tput = self._run_trial(stage, mbs)
            rec = {"zero_stage": stage, "micro_batch_size": mbs,
                   "samples_per_sec": tput}
            self.results.append(rec)
            logger.info(f"autotuner: {rec}")
            if tput is not None and (best is None or tput > best["samples_per_sec"]):
                best = rec
        if best is None:
            raise RuntimeError("autotuner: every trial failed")
        out = dict(self.base_config)
        out["train_micro_batch_size_per_gpu"] = best["micro_batch_size"]
        out.setdefault("zero_optimization", {})
        out["zero_optimization"] = {**out["zero_optimization"],
                                    "stage": best["zero_stage"]}
        self.best = best
        return out
