"""Autotuner (reference `autotuning/autotuner.py:42`).

Same strategy as the reference: estimate ZeRO model-state memory to prune
the space (`:278`), then launch short real runs over (zero stage,
micro-batch) candidates and keep the fastest (`tune:404`). The reference
schedules each experiment as a separate launcher job; on TPU each trial is
an in-process engine build + a few compiled steps (cheap, no process
spawning), which also means the tuner composes with any mesh.
"""

from __future__ import annotations

import gc
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger

TUNING_MICRO_BATCH_SIZES = [1, 2, 4, 8]
TUNING_ZERO_STAGES = [0, 1, 2, 3]


def estimate_zero_memory(num_params: int, stage: int, dp_size: int,
                         bf16: bool = True, gas: int = 2) -> int:
    """Per-device model-state bytes (reference memory estimation `:278` /
    `zero/model_states_mem_needs`): params + grads + Adam(m, v, master)."""
    bytes_per = 2 if bf16 else 4
    p = num_params * bytes_per          # model params
    # fp32 grad accumulation: sharded from stage >= 1 (partition.py
    # grad_accum_spec); fully ELIDED at GAS=1 — callers tuning GAS=1
    # workloads can pass gas=1 for the tighter bound
    g = 0 if gas == 1 else num_params * 4
    o = num_params * 12 if bf16 else num_params * 8  # master + m + v
    if stage >= 3:
        p //= dp_size
    if stage >= 1:
        g //= dp_size
        o //= dp_size
    return p + g + o


# Per-token-per-layer live activation bytes factor by remat policy, in units
# of `hidden` (H) and `intermediate` (I). Whole-block remat ('nothing')
# keeps only the residual stream at block boundaries; 'checkpoint_dots'
# additionally keeps every matmul output (q/k/v/o projections + gate/up/down
# inputs — the policy that OOMed at mbs4 and at 16k ctx on v5e, r2 ledger);
# no remat keeps the full forward. Assumes flash attention (no S² logits).
_REMAT_FACTORS = {
    "nothing": lambda h, i: h,
    # host_offload stages the block-boundary residuals to pinned host
    # memory — their HBM share is ~0; the per-block working set (the
    # separate `working` term) still applies
    "host_offload": lambda h, i: 0,
    "checkpoint_dots": lambda h, i: 4 * h + 3 * i,
    "dots": lambda h, i: 4 * h + 3 * i,
    None: lambda h, i: 14 * h + 4 * i,  # no remat
}


def estimate_activation_memory(mbs: int, seq_len: int, hidden: int,
                               num_layers: int,
                               intermediate: Optional[int] = None,
                               vocab: Optional[int] = None,
                               remat_policy: Optional[str] = "nothing",
                               bytes_per: int = 2) -> int:
    """Per-device activation bytes for one micro-batch of a transformer —
    the term the r2 autotuner ignored (its pruning passed configs whose
    activations then OOMed at trial time; reference `autotuner.py:278`
    prunes on activation_mem too). Three parts: live checkpoints across all
    layers (policy-dependent), one block's recompute working set, and the
    fp32 logits+softmax buffers (elided when the model chunks its loss)."""
    i = intermediate or 4 * hidden
    if remat_policy not in _REMAT_FACTORS:
        raise ValueError(
            f"unknown remat_policy {remat_policy!r} — the estimator would "
            "have to guess its activation footprint (the model falls back "
            "to whole-block remat for unknown names; pass 'nothing' to "
            "estimate that)")
    factor = _REMAT_FACTORS[remat_policy](hidden, i)
    live = mbs * seq_len * num_layers * factor * bytes_per
    working = mbs * seq_len * (4 * hidden + 3 * i) * bytes_per
    logits = 2 * mbs * seq_len * vocab * 4 if vocab else 0
    return live + working + logits



def apply_candidate(base_config: Dict, cand: Dict[str, Any]) -> Dict:
    """Merge a winning candidate into a full engine config — ONE place for
    the mbs/zero-stage placement and the reserved-key exclusions (shared by
    Autotuner.tune and the experiment scheduler)."""
    out = dict(base_config)
    out["train_micro_batch_size_per_gpu"] = cand["micro_batch_size"]
    out.setdefault("zero_optimization", {})
    out["zero_optimization"] = {**out["zero_optimization"],
                                "stage": cand["zero_stage"]}
    for k, v in cand.items():
        if k not in ("zero_stage", "micro_batch_size", "samples_per_sec",
                     "exp_id"):
            out[k] = v
    return out


class Autotuner:
    """Search (zero_stage, micro_batch) by short measured runs.

    build_engine(config_dict) -> engine; batch_fn(mbs) -> global batch.
    """

    def __init__(self, build_engine: Callable[[Dict], Any],
                 batch_fn: Callable[[int], Dict],
                 base_config: Dict,
                 micro_batch_sizes: Optional[List[int]] = None,
                 zero_stages: Optional[List[int]] = None,
                 num_steps: int = 3, warmup: int = 1,
                 max_memory_bytes: Optional[int] = None,
                 num_params: Optional[int] = None,
                 dp_size: int = 1,
                 extra_dims: Optional[Dict[str, List[Any]]] = None,
                 model_info: Optional[Dict[str, int]] = None,
                 memory_safety: float = 0.92):
        """`max_memory_bytes=None` reads the per-device HBM budget from the
        accelerator (reference reads `autotuning.max_train_micro_batch_size`
        memory from the GPU); pass explicitly to override.

        `model_info` ({hidden_size, num_layers, seq_len, intermediate_size?,
        vocab_size?}) enables the ACTIVATION term in pruning — without it
        only model states are estimated and activation-bound configs (large
        mbs, long seq, heavy remat policies) reach trial time before
        failing."""
        self.build_engine = build_engine
        self.batch_fn = batch_fn
        self.base_config = base_config
        self.micro_batch_sizes = micro_batch_sizes or TUNING_MICRO_BATCH_SIZES
        self.zero_stages = zero_stages or TUNING_ZERO_STAGES
        self.num_steps = num_steps
        self.warmup = warmup
        if max_memory_bytes is None:
            from deepspeed_tpu.accelerator import get_accelerator
            total = get_accelerator().total_memory()
            max_memory_bytes = int(total * memory_safety) if total else None
        self.max_memory_bytes = max_memory_bytes
        self.num_params = num_params
        self.dp_size = dp_size
        self.model_info = model_info
        # Extra cross-product search dimensions, e.g.
        # {"remat_policy": ["nothing", "checkpoint_dots"]}: each key lands
        # at the top level of the trial config for build_engine to consume
        # (remat is how the v5e bench went 54% → 59% MFU — it belongs in
        # the search space, reference autotuner's `other flags` role).
        self.extra_dims = extra_dims or {}
        for k in ("zero_stage", "micro_batch_size"):
            if k in self.extra_dims:
                raise ValueError(
                    f"extra_dims[{k!r}] would silently override the swept "
                    "dimension of the same name — use zero_stages/"
                    "micro_batch_sizes instead")
        for k, v in self.extra_dims.items():
            if not v:
                raise ValueError(
                    f"extra_dims[{k!r}] is empty — an empty dimension would "
                    "silently collapse the whole cross-product")
        self.results: List[Dict] = []

    def _estimate(self, stage: int, mbs: int, extra: Dict[str, Any]) -> int:
        """Model-state + activation bytes for one candidate. GAS and remat
        policy are read from the candidate itself (falling back to
        base_config) so swept dimensions shape the estimate."""
        gas = int(extra.get("gradient_accumulation_steps",
                            self.base_config.get(
                                "gradient_accumulation_steps", 1)))
        need = estimate_zero_memory(self.num_params, stage, self.dp_size,
                                    gas=gas)
        if self.model_info:
            mi = self.model_info
            need += estimate_activation_memory(
                mbs, mi["seq_len"], mi["hidden_size"], mi["num_layers"],
                intermediate=mi.get("intermediate_size"),
                vocab=mi.get("vocab_size"),
                remat_policy=extra.get(
                    "remat_policy", self.base_config.get("remat_policy",
                                                         "nothing")))
        return need

    def _candidates(self) -> List[Dict[str, Any]]:
        import itertools
        extras = [dict(zip(self.extra_dims, vals)) for vals in
                  itertools.product(*self.extra_dims.values())] or [{}]
        out = []
        for stage in self.zero_stages:
            for mbs in self.micro_batch_sizes:
                for extra in extras:
                    if self.max_memory_bytes and self.num_params:
                        need = self._estimate(stage, mbs, extra)
                        if need > self.max_memory_bytes:
                            logger.info(
                                f"autotuner: prune stage={stage} mbs={mbs} "
                                f"{extra} (needs {need/1e9:.1f} GB)")
                            continue
                    out.append({"zero_stage": stage, "micro_batch_size": mbs,
                                **extra})
        return out

    def _run_trial(self, cand: Dict[str, Any]) -> Optional[float]:
        import jax
        stage, mbs = cand["zero_stage"], cand["micro_batch_size"]
        cfg = dict(self.base_config)
        cfg["train_micro_batch_size_per_gpu"] = mbs
        cfg.setdefault("zero_optimization", {})
        cfg["zero_optimization"] = {**cfg["zero_optimization"], "stage": stage}
        for k, v in cand.items():
            if k not in ("zero_stage", "micro_batch_size"):
                cfg[k] = v
        engine = None
        samples_s = None
        try:
            engine = self.build_engine(cfg)
            try:  # GAS-aware batch fns take (mbs, candidate_cfg)
                batch = self.batch_fn(mbs, cfg)
            except TypeError:
                batch = self.batch_fn(mbs)
            for _ in range(self.warmup):
                engine.train_batch(batch=batch)
            jax.block_until_ready(engine.state)
            t0 = time.perf_counter()
            for _ in range(self.num_steps):
                loss = engine.train_batch(batch=batch)
            jax.block_until_ready((engine.state, loss))
            dt = time.perf_counter() - t0
            samples_s = engine.train_batch_size() * self.num_steps / dt
        except Exception as e:
            logger.info(f"autotuner: trial {cand} failed: {e}")
        finally:
            # free the trial engine's device state before the next trial —
            # back-to-back HBM-sized optimizer trees otherwise overlap
            if engine is not None:
                engine.state = None
                getattr(engine, "_jit_cache", {}).clear()
            del engine
            gc.collect()
        if samples_s is None:
            # an OOM'd trial's HBM is returned lazily by some runtimes
            # (observed through the axon tunnel: live_arrays() clean but
            # the next trial still ResourceExhausted) — settle AFTER the
            # cleanup above so the window actually covers freed buffers
            time.sleep(float(os.environ.get("DS_TPU_AUTOTUNE_COOLDOWN",
                                            "5")))
        return samples_s

    def tune(self) -> Dict:
        """Reference `tune:404` → best config dict (fastest samples/s)."""
        best = None
        for cand in self._candidates():
            tput = self._run_trial(cand)
            rec = {**cand, "samples_per_sec": tput}
            self.results.append(rec)
            logger.info(f"autotuner: {rec}")
            if tput is not None and (best is None or tput > best["samples_per_sec"]):
                best = rec
        if best is None:
            raise RuntimeError("autotuner: every trial failed")
        self.best = best
        return apply_candidate(self.base_config, best)
