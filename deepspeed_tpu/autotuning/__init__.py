from deepspeed_tpu.autotuning.autotuner import Autotuner, estimate_zero_memory  # noqa: F401
