"""Autotuning driver — the bridge from `ds_tpu --autotuning {tune,run}`
(reference `launcher/runner.py:390`) and the `{"autotuning": {...}}` config
block to the experiment scheduler.

Reference flow: the launcher hands the job to `Autotuner.tune()`, which
schedules short training-script runs with mutated configs across the
cluster, then either stops (mode=tune) or launches the best config
(mode=run). TPU flow: trials are in-process engine builds, so the USER
SCRIPT'S OWN `deepspeed_tpu.initialize()` call becomes the tuning driver —
the launcher only sets `DS_TPU_AUTOTUNING`; when initialize() sees it (or
an enabled autotuning config block), it sweeps candidates around the
model/config it was about to build, persists results, and then continues
with the winning config (run) or exits (tune).

Model-side knobs (remat_policy) are swept by rebuilding the flax module
with `dataclasses.replace(model.cfg, ...)` — on TPU the remat policy is a
property of the compiled step, exactly the kind of "other flag" the
reference tuner mutates in the ds_config.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, Optional

from deepspeed_tpu.utils.logging import logger

_ACTIVE_ENV = "_DS_TPU_AUTOTUNING_ACTIVE"


def autotuning_requested(raw_cfg: Any) -> Optional[str]:
    """Return the requested mode ('tune' | 'run') or None. Guarded so the
    trial engines the driver builds don't recurse into the driver."""
    if os.environ.get(_ACTIVE_ENV):
        return None
    mode = os.environ.get("DS_TPU_AUTOTUNING", "").strip().lower()
    at = (raw_cfg or {}).get("autotuning", {}) if isinstance(raw_cfg, dict) \
        else {}
    if mode in ("tune", "run"):
        return mode
    if at.get("enabled"):
        return str(at.get("mode", "run")).lower()
    return None


def _model_info_from(model) -> Optional[Dict[str, int]]:
    cfg = getattr(model, "cfg", None)
    if cfg is None:
        return None
    try:
        return {
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_hidden_layers,
            "seq_len": min(getattr(cfg, "max_position_embeddings", 2048),
                           2048),
            "intermediate_size": getattr(cfg, "intermediate_size", None),
            "vocab_size": getattr(cfg, "vocab_size", None),
        }
    except AttributeError:
        return None


def run_autotuning(model, model_parameters, raw_cfg: Dict, loss_fn,
                   base_param_specs, mode: str,
                   initialize_fn: Callable) -> Dict:
    """Sweep candidates around (model, raw_cfg); persist results; return
    the best full config. `initialize_fn` is deepspeed_tpu.initialize —
    passed in to avoid a circular import."""
    import jax
    import numpy as np

    from deepspeed_tpu.autotuning.autotuner import Autotuner
    from deepspeed_tpu.autotuning.scheduler import ExperimentScheduler
    from deepspeed_tpu.utils import groups

    at_cfg = dict(raw_cfg.get("autotuning", {}) or {})
    base = {k: v for k, v in raw_cfg.items() if k != "autotuning"}
    results_dir = os.environ.get(
        "DS_TPU_AUTOTUNING_DIR",
        at_cfg.get("results_dir", "autotuning_results"))

    mi = _model_info_from(model)
    seq_len = int(at_cfg.get("seq_len", (mi or {}).get("seq_len", 512)))
    if mi:
        mi["seq_len"] = seq_len
    vocab = (mi or {}).get("vocab_size") or 1024

    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(model_parameters))

    loss_fn_builder = at_cfg.get("loss_fn_builder")
    sweeps_model = bool(at_cfg.get("remat_policy"))
    if sweeps_model and loss_fn_builder is None:
        raise ValueError(
            "autotuning.remat_policy sweeps rebuild the model, but the "
            "zoo loss fns close over the model instance — pass "
            "autotuning.loss_fn_builder (model -> loss_fn), e.g. "
            "llama_loss_fn, so each trial's loss drives ITS model")
    if sweeps_model and not (hasattr(model, "cfg") and
                             hasattr(getattr(model, "cfg"), "remat_policy")):
        raise ValueError(
            "autotuning.remat_policy swept but the model's cfg has no "
            "remat_policy field — every trial would silently run the SAME "
            "model while the results claim distinct policies")

    def build_engine(cfg: Dict) -> Any:
        os.environ[_ACTIVE_ENV] = "1"
        try:
            groups.reset_topology()
            trial_model, trial_loss = model, loss_fn
            policy = cfg.pop("remat_policy", None)
            if policy is not None and hasattr(model, "cfg") and \
                    hasattr(model.cfg, "remat_policy"):
                trial_model = type(model)(
                    cfg=dataclasses.replace(model.cfg, remat=True,
                                            remat_policy=policy))
                trial_loss = loss_fn_builder(trial_model)
            engine, *_ = initialize_fn(
                model=trial_model, model_parameters=model_parameters,
                config=cfg, loss_fn=trial_loss,
                base_param_specs=base_param_specs)
            return engine
        finally:
            os.environ.pop(_ACTIVE_ENV, None)

    rng = np.random.default_rng(0)

    def batch_fn(mbs: int, cfg: Optional[Dict] = None) -> Dict:
        gas = int((cfg or {}).get(
            "gradient_accumulation_steps",
            base.get("gradient_accumulation_steps", 1)))
        try:
            dp = groups.get_topology(create_default=False).dp_size
        except RuntimeError:
            dp = 1
        rows = mbs * gas * dp
        return {"input_ids": rng.integers(
            0, vocab, size=(rows, seq_len)).astype(np.int32)}

    extra_dims = dict(at_cfg.get("extra_dims", {}) or {})
    if "remat_policy" in at_cfg:
        extra_dims["remat_policy"] = at_cfg["remat_policy"]

    # dp for the ZeRO memory estimator: devices not claimed by other axes
    # (hard-coding 1 would leave states unsharded in the estimate and
    # wrongly prune stage>=1 candidates on real dp>1 meshes)
    tp = int((base.get("tensor_parallel", {}) or {}).get("tp_size", 1)) or 1
    other = tp * int(base.get("sequence_parallel_size", 1)) * \
        int(base.get("expert_parallel_size", 1)) * \
        int((base.get("pipeline", {}) or {}).get("pipeline_parallel_size", 1))
    dp = max(1, jax.device_count() // max(other, 1))

    tuner = Autotuner(
        build_engine=build_engine, batch_fn=batch_fn, base_config=base,
        micro_batch_sizes=at_cfg.get("micro_batch_sizes"),
        zero_stages=at_cfg.get("zero_stages"),
        num_steps=int(at_cfg.get("num_tuning_steps", 3)),
        warmup=int(at_cfg.get("warmup_steps", 1)),
        num_params=n_params,
        dp_size=dp,
        extra_dims=extra_dims, model_info=mi)
    sched = ExperimentScheduler(
        tuner, results_dir=results_dir,
        tuner=at_cfg.get("tuner", "model_based"))
    best = sched.run()
    logger.info(f"autotuning ({mode}): best config written to "
                f"{os.path.join(sched.results_dir, 'best.json')}")
    groups.reset_topology()
    # mode=run continues training: model-side knobs in the winner must be
    # APPLIED, not just recorded — rebuild the model (and its loss) with
    # the winning remat policy and strip the key the engine config schema
    # doesn't know
    best_model, best_loss = model, loss_fn
    policy = best.pop("remat_policy", None)
    if policy is not None and hasattr(model, "cfg") and \
            hasattr(model.cfg, "remat_policy"):
        best_model = type(model)(
            cfg=dataclasses.replace(model.cfg, remat=True,
                                    remat_policy=policy))
        best_loss = loss_fn_builder(best_model)
        logger.info(f"autotuning: continuing with remat_policy={policy!r}")
    return best, best_model, best_loss
