"""Elastic training config (reference `elasticity/elasticity.py:233`).

Computes world-size-compatible batch configurations: given micro-batch
candidates and a max acceptable global batch, find the golden batch size
that admits the most divisor world sizes (v0.1 `:83`) and per-world-size
(micro_batch, gradient_accumulation) splits (v0.2 `:126`). Recovery on TPU
is checkpoint-based: a resize re-runs `compute_elastic_config` for the new
chip count and resumes via the universal-checkpoint reshape — there is no
torch-elastic agent process to port (`DSElasticAgent`), the cluster manager
owns process lifecycle.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

LATEST_ELASTICITY_VERSION = 0.2


class ElasticityError(Exception):
    pass


def get_valid_gbs(micro_batches: List[int], max_acceptable_batch_size: int,
                  min_gpus: int, max_gpus: int) -> List[int]:
    """All global batch sizes = mb * gas * world reachable under the cap."""
    valid = set()
    for mb in micro_batches:
        b = mb
        while b <= max_acceptable_batch_size:
            valid.add(b)
            b += mb
    return sorted(valid)


def get_compatible_gpus(micro_batches: List[int], batch_size: int,
                        min_gpus: int = 1, max_gpus: int = 10000
                        ) -> List[int]:
    """World sizes that evenly consume `batch_size` with some (mb, gas)
    (reference `_get_compatible_gpus_v01`)."""
    out = set()
    for w in range(min_gpus, max_gpus + 1):
        for mb in micro_batches:
            if batch_size % (w * mb) == 0:
                out.add(w)
                break
    return sorted(out)


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """Reference `compute_elastic_config:233`: pick the golden global batch
    size (max compatible world sizes, then largest batch) and, when
    `world_size` is known, the (micro_batch, gas) pair for it."""
    e = ds_config.get("elasticity")
    if not e:
        raise ElasticityError("'elasticity' block missing from config")
    if not e.get("enabled", False):
        raise ElasticityError("elasticity.enabled is false")
    micro_batches = sorted(e["micro_batch_sizes"], reverse=True)
    # reference JSON schema key is 'max_train_batch_size'
    # (elasticity/constants.py:MAX_ACCEPTABLE_BATCH_SIZE); accept the
    # internal attribute name too for backward compat
    if "max_train_batch_size" in e:
        max_b = int(e["max_train_batch_size"])
    else:
        max_b = int(e["max_acceptable_batch_size"])
    min_gpus = int(e.get("min_gpus", 1))
    max_gpus = int(e.get("max_gpus", 10000))
    prefer_larger = bool(e.get("prefer_larger_batch", True))

    candidates = get_valid_gbs(micro_batches, max_b, min_gpus, max_gpus)
    best: Tuple[int, int] = (0, 0)  # (num compatible gpus, batch)
    best_gpus: List[int] = []
    for b in candidates:
        gpus = get_compatible_gpus(micro_batches, b, min_gpus, max_gpus)
        key = (len(gpus), b if prefer_larger else -b)
        if key > (best[0], best[1] if prefer_larger else -best[1]):
            best = (len(gpus), b)
            best_gpus = gpus
    if not best_gpus:
        raise ElasticityError(
            f"no compatible world size for micro_batches={micro_batches}, "
            f"max batch {max_b}")
    final_batch = best[1]

    if world_size > 0:
        if world_size not in best_gpus:
            raise ElasticityError(
                f"world size {world_size} not compatible with batch "
                f"{final_batch}; valid: {best_gpus}")
        for mb in micro_batches:  # largest usable micro-batch first
            if final_batch % (world_size * mb) == 0:
                micro = mb
                break
        if return_microbatch:
            return final_batch, best_gpus, micro
        return final_batch, best_gpus
    if return_microbatch:
        return final_batch, best_gpus, None
    return final_batch, best_gpus
