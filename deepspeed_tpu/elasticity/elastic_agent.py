"""Elastic agent (reference `elasticity/elastic_agent.py:32` `DSElasticAgent`).

The reference extends torch-elastic's `LocalElasticAgent`: watch workers,
on failure tear the group down and restart it with DS env injected, letting
training resume from the latest checkpoint. The TPU agent is the same
supervise-and-restart loop over `jax.distributed` workers:

- spawn N rendezvous-connected worker processes (fresh coordinator port per
  generation — a dead coordinator must not wedge the next one);
- on any worker failure: kill the generation, recompute the elastic batch
  config for the (possibly changed) world size
  (`elasticity.compute_elastic_config`), and restart;
- workers see `DS_ELASTIC_RESTART_COUNT`, `DS_ELASTIC_MICRO_BATCH` and
  `DS_ELASTIC_GAS` and are expected to `load_checkpoint(latest)` on entry —
  recovery is checkpoint-based (universal reshape handles resizes).
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from deepspeed_tpu.utils.logging import logger


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class DSElasticAgent:
    def __init__(self, script: str, script_args: Optional[Sequence[str]] = None,
                 num_procs: int = 2, master_addr: str = "127.0.0.1",
                 max_restarts: int = 3, ds_config: Optional[Dict] = None,
                 monitor_interval: float = 0.25,
                 env: Optional[Dict[str, str]] = None,
                 generation_timeout: Optional[float] = None,
                 straggler_grace: Optional[float] = None):
        """`generation_timeout`: wall-clock cap per generation — a worker
        hung in a dead collective (the common failure after a peer loss in
        jax.distributed) never EXITS, so exit-code monitoring alone waits
        forever; on expiry the generation is killed and restarted (the
        torch-elastic watchdog role). `straggler_grace`: once any worker
        has exited CLEANLY, peers still running this long after are
        presumed hung and the generation is torn down. Both are OPT-IN
        (None = off): a too-small grace would kill legitimate stragglers
        like rank 0 writing the final checkpoint after its peers exit —
        size it well above your checkpoint/teardown time."""
        self.script = script
        self.script_args = list(script_args or [])
        self.num_procs = num_procs
        self.master_addr = master_addr
        self.max_restarts = max_restarts
        self.ds_config = ds_config
        self.monitor_interval = monitor_interval
        self.extra_env = dict(env or {})
        self.generation_timeout = generation_timeout
        self.straggler_grace = straggler_grace
        self.restart_count = 0

    # ------------------------------------------------------------------
    def _elastic_env(self, world: int) -> Dict[str, str]:
        """DS env injection (reference `elastic_agent.py:65`
        `_set_master_addr_port` + DS config env): per-world-size batch
        split from the elasticity config, if one is present."""
        env = {"DS_ELASTIC_RESTART_COUNT": str(self.restart_count),
               "DS_ELASTIC_WORLD_SIZE": str(world)}
        if self.ds_config and self.ds_config.get("elasticity", {}).get("enabled"):
            from deepspeed_tpu.elasticity.elasticity import (
                compute_elastic_config)
            final_batch, valid_gpus, mbs = compute_elastic_config(
                self.ds_config, world_size=world, return_microbatch=True)
            gas = final_batch // (mbs * world)
            env.update({"DS_ELASTIC_GLOBAL_BATCH": str(final_batch),
                        "DS_ELASTIC_MICRO_BATCH": str(mbs),
                        "DS_ELASTIC_GAS": str(gas)})
        return env

    def _compatible_world(self, world: int) -> int:
        """Clamp a resized world to the NEAREST batch-compatible size at or
        below it (ADVICE r3: an uncaught ElasticityError here used to crash
        the supervisor mid-run). The ADJUSTED size is what gets SPAWNED —
        the generation really runs with that many workers, so the jax
        rendezvous, DS_ELASTIC_* env, and realized global batch all agree."""
        if not (self.ds_config and
                self.ds_config.get("elasticity", {}).get("enabled")):
            return world
        from deepspeed_tpu.elasticity.elasticity import (
            ElasticityError, compute_elastic_config)
        try:
            compute_elastic_config(self.ds_config, world_size=world)
            return world
        except ElasticityError:
            _, valid_gpus = compute_elastic_config(self.ds_config)
            usable = [g for g in valid_gpus if g <= world]
            if not usable:
                raise ElasticityError(
                    f"world size {world} has no compatible size at or "
                    f"below it (valid: {sorted(valid_gpus)}) — cannot "
                    "restart; raise max_acceptable_batch_size or add "
                    "workers") from None
            adjusted = max(usable)
            logger.warning(
                f"elastic agent: resized world {world} incompatible; "
                f"spawning nearest compatible world size {adjusted}")
            return adjusted

    def _spawn(self, world: int) -> List[subprocess.Popen]:
        port = _free_port()
        procs = []
        base = {**os.environ, **self.extra_env, **self._elastic_env(world)}
        for rank in range(world):
            env = dict(base)
            env.update({
                "COORDINATOR_ADDRESS": f"{self.master_addr}:{port}",
                "JAX_NUM_PROCESSES": str(world),
                "JAX_PROCESS_ID": str(rank),
                "RANK": str(rank),
                "LOCAL_RANK": str(rank),
                "WORLD_SIZE": str(world),
            })
            cmd = [sys.executable, self.script] + self.script_args
            procs.append(subprocess.Popen(cmd, env=env))
        logger.info(f"elastic agent: generation {self.restart_count} — "
                    f"{world} workers @ {self.master_addr}:{port}")
        return procs

    def _teardown(self, procs: List[subprocess.Popen]) -> None:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()

    def _monitor(self, procs: List[subprocess.Popen]) -> int:
        """Wait until every worker exits 0 (→0) or any fails (→its rc,
        after tearing the generation down — reference torch-elastic
        monitor loop semantics). Hang protection: a generation-wide
        wall-clock timeout plus a straggler watchdog (workers still
        running long after a peer exited are presumed stuck in a dead
        collective) — both kill the generation and report failure so the
        supervisor restarts it."""
        start = time.time()
        first_exit: Optional[float] = None
        while True:
            rcs = [p.poll() for p in procs]
            failed = [rc for rc in rcs if rc not in (None, 0)]
            if failed:
                self._teardown(procs)
                return failed[0]
            if all(rc == 0 for rc in rcs):
                return 0
            now = time.time()
            if first_exit is None and any(rc == 0 for rc in rcs):
                first_exit = now
            if self.generation_timeout and \
                    now - start > self.generation_timeout:
                # each fire is a distinct generation kill, not loop spam
                logger.warning("elastic agent: generation exceeded "  # tpulint: disable=warn-once-discipline
                               f"{self.generation_timeout}s — killing "
                               "presumed-hung workers")
                self._emit_watchdog("generation_timeout",
                                    self.generation_timeout)
                self._teardown(procs)
                return 124
            if self.straggler_grace is not None and first_exit is not None \
                    and now - first_exit > self.straggler_grace:
                # each fire is a distinct straggler kill, not loop spam
                logger.warning("elastic agent: workers still running "  # tpulint: disable=warn-once-discipline
                               f"{self.straggler_grace}s after a peer "
                               "exited — killing presumed-hung stragglers")
                self._emit_watchdog("straggler_grace", self.straggler_grace)
                self._teardown(procs)
                return 125
            time.sleep(self.monitor_interval)

    def _emit_watchdog(self, watchdog: str, timeout_s: float) -> None:
        """`watchdog` telemetry event for the agent's own hang protection —
        same append-only schema as the serving watchdogs
        (docs/telemetry.md), so generation kills land in the one JSONL
        stream."""
        from deepspeed_tpu.resilience.faults import _emit_event
        _emit_event("watchdog", watchdog=watchdog, timeout_s=timeout_s,
                    generation=self.restart_count, fallback="restart")

    def run(self, num_procs_per_generation: Optional[Sequence[int]] = None
            ) -> int:
        """Supervise until success or restart budget exhausted. An optional
        per-generation world-size sequence models resizes (the agent of a
        shrinking cluster); default keeps `num_procs`."""
        gen = 0
        while True:
            world = (num_procs_per_generation[min(
                gen, len(num_procs_per_generation) - 1)]
                if num_procs_per_generation else self.num_procs)
            world = self._compatible_world(world)
            procs = self._spawn(world)
            rc = self._monitor(procs)
            if rc == 0:
                logger.info("elastic agent: job completed")
                return 0
            self.restart_count += 1
            gen += 1
            if self.restart_count > self.max_restarts:
                logger.error(f"elastic agent: giving up after "
                             f"{self.max_restarts} restarts (rc={rc})")
                return rc
            # one warning PER RESTART is the contract, not log spam
            logger.warning(f"elastic agent: worker failed (rc={rc}); "  # tpulint: disable=warn-once-discipline
                           f"restart {self.restart_count}/{self.max_restarts}")
            from deepspeed_tpu.resilience.faults import _emit_event
            _emit_event("elastic_restart", rc=int(rc),
                        generation=self.restart_count, world=int(world))
