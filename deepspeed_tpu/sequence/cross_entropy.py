"""Sequence-parallel / memory-chunked cross entropy.

Counterpart of reference `deepspeed/sequence/cross_entropy.py`
(`vocab_sequence_parallel_cross_entropy`) and the FPDT chunked-loss path
(`sequence/fpdt_layer.py:1137`). The reference splits the vocab matmul per
TP rank and all-reduces partial logsumexps; here the chunking is over the
*sequence* axis — per chunk we compute (B, C, V) logits, reduce them to a
per-token loss, and drop them before the next chunk, under `jax.checkpoint`
so the backward recomputes each chunk instead of storing it. Vocab-parallel
TP falls out declaratively: with `lm_head` sharded over 'model' on the vocab
dim, XLA reduces the chunk logsumexp across TP ranks.

Peak logits memory: O(B · chunk · V) instead of O(B · S · V) — the piece
that makes 128k-context training (BASELINE config 5) fit.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def chunked_softmax_cross_entropy(h: jnp.ndarray, lm_head, labels: jnp.ndarray,
                                  chunk_size: int = 2048,
                                  ignore_index: int = -100,
                                  tied_embedding: bool = False) -> jnp.ndarray:
    """Mean token CE of `h @ lm_head` against `labels` without materializing
    the full (B, S, V) logits.

    h: (B, S, D); lm_head: (D, V) — or (V, D) with `tied_embedding=True`;
    labels: (B, S) int32, `ignore_index` masks tokens out.
    """
    b, s, d = h.shape
    chunk = min(chunk_size, s)
    while s % chunk:
        chunk -= 1
    n = s // chunk
    hc = h.reshape(b, n, chunk, d)
    yc = labels.reshape(b, n, chunk)

    def body(carry, xs):
        loss_sum, count = carry
        h_blk, y_blk = xs  # (B, C, D), (B, C)
        if tied_embedding:
            logits = jnp.einsum("bcd,vd->bcv", h_blk, lm_head)
        else:
            logits = h_blk @ lm_head
        logits = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        y_safe = jnp.clip(y_blk, 0, logits.shape[-1] - 1)
        gold = jnp.take_along_axis(logits, y_safe[..., None], axis=-1)[..., 0]
        mask = (y_blk != ignore_index).astype(jnp.float32)
        loss_sum = loss_sum + jnp.sum((lse - gold) * mask)
        count = count + jnp.sum(mask)
        return (loss_sum, count), None

    body = jax.checkpoint(body, prevent_cse=False)
    (loss_sum, count), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (jnp.moveaxis(hc, 1, 0), jnp.moveaxis(yc, 1, 0)))
    return loss_sum / jnp.maximum(count, 1.0)


def vocab_sequence_parallel_cross_entropy(h, lm_head, labels, chunk_size=2048,
                                          **kwargs) -> jnp.ndarray:
    """Reference-name alias (`sequence/cross_entropy.py`)."""
    return chunked_softmax_cross_entropy(h, lm_head, labels,
                                         chunk_size=chunk_size, **kwargs)
