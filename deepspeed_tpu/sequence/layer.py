"""Ulysses sequence parallelism.

Counterpart of the reference's `deepspeed/sequence/layer.py:300`
(`DistributedAttention`) and `_SeqAllToAll:245` / `single_all_to_all:182`.

DeepSpeed-Ulysses: activations are sharded along the sequence dimension; just
before attention an all-to-all re-shards them along the *heads* dimension
(gathering the full sequence per head), local attention runs on full sequence
with 1/P of the heads, and a second all-to-all restores sequence sharding.
Comm volume is O(N/P) per step — the property the reference claims at
`blogs/deepspeed-ulysses/README.md:83-109`.

TPU-native realization: the two all-to-alls are expressed as *sharding
constraints* — seq-sharded → head-sharded → seq-sharded — and XLA's SPMD
partitioner emits exactly one `all-to-all` over the `sequence` mesh axis for
each transition, riding ICI. Overlap with q/k/v projections (reference
`layer.py:361-395` side streams) falls out of XLA's latency-hiding scheduler.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from deepspeed_tpu.ops.attention import repeat_kv
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.partitioning import BATCH_AXES, shard_along


def _sp_size() -> int:
    try:
        return groups.get_topology(create_default=False).sp_size
    except RuntimeError:
        return 1


class DistributedAttention:
    """Wrap a local attention fn with Ulysses head-scatter/seq-gather a2a.

    `local_attention(q, k, v, **kwargs)` sees the full sequence with heads
    partitioned over the `sequence` axis. Inputs/outputs are (B, S, H, D)
    sharded along S.
    """

    def __init__(self, local_attention: Callable, scatter_idx: int = 2,
                 gather_idx: int = 1):
        self.local_attn = local_attention
        self.scatter_idx = scatter_idx  # heads dim (API parity; fixed layout here)
        self.gather_idx = gather_idx    # seq dim

    def __call__(self, query, key, value, *args, **kwargs):
        sp = _sp_size()
        if sp == 1:
            return self.local_attn(query, key, value, *args, **kwargs)
        h, hkv = query.shape[2], key.shape[2]
        pad = (-h) % sp
        if pad or hkv % sp:
            # Uneven heads (reference layer.py:72 get_shard_size tables):
            # expand GQA → MHA and zero-pad the head dim to a multiple of sp;
            # the padded heads attend zeros and are sliced off afterwards.
            if hkv != h:
                key = repeat_kv(key, h // hkv)
                value = repeat_kv(value, h // hkv)
            if pad:
                widths = ((0, 0), (0, 0), (0, pad), (0, 0))
                query = jnp.pad(query, widths)
                key = jnp.pad(key, widths)
                value = jnp.pad(value, widths)
        # head-scatter / seq-gather all-to-all (reference single_all_to_all:182)
        query = shard_along(query, BATCH_AXES, None, "sequence", None)
        key = shard_along(key, BATCH_AXES, None, "sequence", None)
        value = shard_along(value, BATCH_AXES, None, "sequence", None)
        ctx = self.local_attn(query, key, value, *args, **kwargs)
        if pad:
            ctx = ctx[:, :, :h]
        # seq-scatter / head-gather back (reference layer.py:398 output a2a)
        return shard_along(ctx, BATCH_AXES, "sequence", None, None)


class UlyssesAttention(DistributedAttention):
    """Alias matching the reference export name."""
