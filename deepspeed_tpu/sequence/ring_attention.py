"""Ring attention — context parallelism over the `sequence` mesh axis.

The reference has no ring attention in-tree (SURVEY §2.3: Ulysses + FPDT
fill the role); this is the TPU-native completion of that gap. Ulysses
re-shards heads and is limited to sp ≤ num_kv_heads; ring attention keeps
Q/K/V sequence-sharded and rotates the KV chunks around the `sequence` ring
with `ppermute` (one neighbor hop per step, riding ICI), merging per-chunk
attention with the online-softmax recurrence (Liu et al., Ring Attention
with Blockwise Transformers). Memory per device is O(S/P · S/P) logits;
comm per step is the KV chunk — bandwidth-optimal context parallelism with
no head-count constraint.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils import groups


BLOCK_Q = 1024
BLOCK_K = 1024


def _chunk_attend(q, k, v, q_pos0: jnp.ndarray, k_pos0: jnp.ndarray,
                  scale: float, causal: bool, axis: Optional[str] = None):
    """Partial attention of local q against one KV chunk with absolute
    positions, BLOCKWISE: a double scan over (q, kv) tiles with the
    online-softmax recurrence keeps live logits at O(block_q·block_k)
    instead of materializing the (b, h, Sl, Sl) fp32 score matrix per hop —
    the flash-style inner loop Ring Attention assumes (Liu et al.; r2
    verdict weak #4). Returns per-position (m, l, acc) contributions for
    the ring merge. k/v may be GQA (fewer heads) — expanded here, AFTER
    the ring hop, so the rotation moves only the small KV."""
    if k.shape[2] != q.shape[2]:
        from deepspeed_tpu.ops.attention import repeat_kv
        k = repeat_kv(k, q.shape[2] // k.shape[2])
        v = repeat_kv(v, q.shape[2] // v.shape[2])
    b, sq, h, d = q.shape
    sk = k.shape[1]
    bq = min(BLOCK_Q, sq)
    while sq % bq:
        bq -= 1
    bk = min(BLOCK_K, sk)
    while sk % bk:
        bk -= 1
    nq, nk = sq // bq, sk // bk
    qt = jnp.swapaxes(q, 1, 2).reshape(b, h, nq, bq, d)
    kt = jnp.swapaxes(k, 1, 2).reshape(b, h, nk, bk, d)
    vt = jnp.swapaxes(v, 1, 2).reshape(b, h, nk, bk, d)

    def q_block(_, qi):
        qb = qt[:, :, qi] * scale                       # (b, h, bq, d)

        def kv_block(state, ki):
            m, l, acc = state
            s = jnp.einsum("bhqd,bhkd->bhqk", qb, kt[:, :, ki],
                           preferred_element_type=jnp.float32)
            if causal:
                rows = q_pos0 + qi * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0)
                cols = k_pos0 + ki * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1)
                s = jnp.where(cols <= rows, s, -jnp.inf)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
            p = jnp.exp(s - m_safe)
            alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * alpha + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(vt.dtype), vt[:, :, ki],
                preferred_element_type=jnp.float32)
            return (m_new, l, acc), None

        init = (jnp.full((b, h, bq, 1), -jnp.inf, jnp.float32),
                jnp.zeros((b, h, bq, 1), jnp.float32),
                jnp.zeros((b, h, bq, d), jnp.float32))
        if axis is not None:
            # inside the ring's manual region the carries must be born
            # axis-varying to match the (sharded) kv-derived outputs
            init = jax.tree_util.tree_map(
                lambda x: jax.lax.pcast(x, (axis,), to="varying"), init)
        (m, l, acc), _ = jax.lax.scan(kv_block, init, jnp.arange(nk))
        return None, (m, l, acc)

    _, (ms, ls, accs) = jax.lax.scan(q_block, None, jnp.arange(nq))
    m = jnp.moveaxis(ms, 0, 2).reshape(b, h, sq, 1)
    l = jnp.moveaxis(ls, 0, 2).reshape(b, h, sq, 1)
    acc = jnp.moveaxis(accs, 0, 2).reshape(b, h, sq, d)
    return m, l, acc


def _ring_body(q, k, v, axis: str, causal: bool, scale: float):
    """shard_map body: q (B, Sl, H, D), k/v (B, Sl, Hkv, D) — this device's
    sequence chunks. KV rotates un-expanded (GQA stays small on the wire)."""
    from deepspeed_tpu.comm.comms_logging import get_comms_logger
    # ring attention is already in the 0.4.x-SIGABRT program class; the
    # fast AttributeError here is the intended failure mode (jax_compat)
    p_size = jax.lax.axis_size(axis)  # tpulint: disable=no-set-mesh
    r = jax.lax.axis_index(axis)
    b, sl, h, d = q.shape
    q_pos0 = r * sl

    def merge(state, contrib):
        m, l, acc = state
        mi, li, acci = contrib
        m_new = jnp.maximum(m, mi)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        a_old = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        a_new = jnp.where(jnp.isneginf(mi), 0.0, jnp.exp(mi - m_safe))
        return (m_new, l * a_old + li * a_new, acc * a_old + acci * a_new)

    # local chunk first; then p-1 rotations (no dead final hop)
    state = _chunk_attend(q, k, v, q_pos0, r * sl, scale, causal, axis)
    perm = [(j, (j + 1) % p_size) for j in range(p_size)]
    get_comms_logger().record(
        "ppermute", 2 * (p_size - 1) * k.size * k.dtype.itemsize)

    def step(carry, i):
        m, l, acc, kc, vc = carry
        # ring attention's KV rotation IS the wire format (manual region)
        # tpulint: disable-next-line=raw-collective-discipline
        kc = jax.lax.ppermute(kc, axis, perm)
        # tpulint: disable-next-line=raw-collective-discipline — same ring
        vc = jax.lax.ppermute(vc, axis, perm)
        src = (r - i) % p_size          # whose chunk we now hold
        contrib = _chunk_attend(q, kc, vc, q_pos0, src * sl, scale, causal, axis)
        m, l, acc = merge((m, l, acc), contrib)
        return (m, l, acc, kc, vc), None

    if p_size > 1:
        (m, l, acc, _, _), _ = jax.lax.scan(
            step, (*state, k, v), jnp.arange(1, p_size))
    else:
        m, l, acc = state
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return jnp.swapaxes(out.astype(q.dtype), 1, 2)


def ring_attention(q, k, v, causal: bool = True,
                   softmax_scale: Optional[float] = None,
                   axis: str = "sequence", mesh=None) -> jnp.ndarray:
    """q/k/v: (B, S, H, D) global arrays, sequence-sharded over `axis`.
    Returns (B, S, H, D) with the same sharding."""
    if mesh is None:
        mesh = groups.get_mesh()
    if dict(mesh.shape).get(axis, 1) == 1:
        from deepspeed_tpu.ops.attention import reference_attention
        return reference_attention(q, k, v, causal=causal,
                                   softmax_scale=softmax_scale)
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / (d ** 0.5)
    spec = P(None, axis, None, None)
    fn = jax.shard_map(
        lambda q, k, v: _ring_body(q, k, v, axis, causal, scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names={axis})
    return fn(q, k, v)


class RingAttention:
    """Context-parallel drop-in with the DistributedAttention call shape."""

    def __init__(self, softmax_scale: Optional[float] = None,
                 causal: bool = True):
        self.scale = softmax_scale
        self.causal = causal

    def __call__(self, q, k, v, *args, **kwargs):
        # GQA rotates un-expanded; _chunk_attend repeats after each hop
        return ring_attention(q, k, v, causal=self.causal,
                              softmax_scale=self.scale)
