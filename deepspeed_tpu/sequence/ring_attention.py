"""Ring attention — context parallelism over the `sequence` mesh axis.

The reference has no ring attention in-tree (SURVEY §2.3: Ulysses + FPDT
fill the role); this is the TPU-native completion of that gap. Ulysses
re-shards heads and is limited to sp ≤ num_kv_heads; ring attention keeps
Q/K/V sequence-sharded and rotates the KV chunks around the `sequence` ring
with `ppermute` (one neighbor hop per step, riding ICI), merging per-chunk
attention with the online-softmax recurrence (Liu et al., Ring Attention
with Blockwise Transformers). Memory per device is O(S/P · S/P) logits;
comm per step is the KV chunk — bandwidth-optimal context parallelism with
no head-count constraint.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils import groups


def _chunk_attend(q, k, v, q_pos0: jnp.ndarray, k_pos0: jnp.ndarray,
                  scale: float, causal: bool):
    """Partial attention of local q against one KV chunk with absolute
    positions. Returns (m, l, acc) contributions. k/v may be GQA
    (fewer heads) — expanded here, AFTER the ring hop, so the rotation
    moves only the small KV."""
    if k.shape[2] != q.shape[2]:
        from deepspeed_tpu.ops.attention import repeat_kv
        k = repeat_kv(k, q.shape[2] // k.shape[2])
        v = repeat_kv(v, q.shape[2] // v.shape[2])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        rows = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = k_pos0 + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(cols <= rows, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)                      # (b,h,q,1)
    m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return m, l, acc


def _ring_body(q, k, v, axis: str, causal: bool, scale: float):
    """shard_map body: q (B, Sl, H, D), k/v (B, Sl, Hkv, D) — this device's
    sequence chunks. KV rotates un-expanded (GQA stays small on the wire)."""
    from deepspeed_tpu.comm.comms_logging import get_comms_logger
    p_size = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    b, sl, h, d = q.shape
    q_pos0 = r * sl

    def merge(state, contrib):
        m, l, acc = state
        mi, li, acci = contrib
        m_new = jnp.maximum(m, mi)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        a_old = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
        a_new = jnp.where(jnp.isneginf(mi), 0.0, jnp.exp(mi - m_safe))
        return (m_new, l * a_old + li * a_new, acc * a_old + acci * a_new)

    # local chunk first; then p-1 rotations (no dead final hop)
    state = _chunk_attend(q, k, v, q_pos0, r * sl, scale, causal)
    perm = [(j, (j + 1) % p_size) for j in range(p_size)]
    get_comms_logger().record(
        "ppermute", 2 * (p_size - 1) * k.size * k.dtype.itemsize)

    def step(carry, i):
        m, l, acc, kc, vc = carry
        kc = jax.lax.ppermute(kc, axis, perm)
        vc = jax.lax.ppermute(vc, axis, perm)
        src = (r - i) % p_size          # whose chunk we now hold
        contrib = _chunk_attend(q, kc, vc, q_pos0, src * sl, scale, causal)
        m, l, acc = merge((m, l, acc), contrib)
        return (m, l, acc, kc, vc), None

    if p_size > 1:
        (m, l, acc, _, _), _ = jax.lax.scan(
            step, (*state, k, v), jnp.arange(1, p_size))
    else:
        m, l, acc = state
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return jnp.swapaxes(out.astype(q.dtype), 1, 2)


def ring_attention(q, k, v, causal: bool = True,
                   softmax_scale: Optional[float] = None,
                   axis: str = "sequence", mesh=None) -> jnp.ndarray:
    """q/k/v: (B, S, H, D) global arrays, sequence-sharded over `axis`.
    Returns (B, S, H, D) with the same sharding."""
    if mesh is None:
        mesh = groups.get_mesh()
    if dict(mesh.shape).get(axis, 1) == 1:
        from deepspeed_tpu.ops.attention import reference_attention
        return reference_attention(q, k, v, causal=causal,
                                   softmax_scale=softmax_scale)
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / (d ** 0.5)
    spec = P(None, axis, None, None)
    fn = jax.shard_map(
        lambda q, k, v: _ring_body(q, k, v, axis, causal, scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names={axis})
    return fn(q, k, v)


class RingAttention:
    """Context-parallel drop-in with the DistributedAttention call shape."""

    def __init__(self, softmax_scale: Optional[float] = None,
                 causal: bool = True):
        self.scale = softmax_scale
        self.causal = causal

    def __call__(self, q, k, v, *args, **kwargs):
        # GQA rotates un-expanded; _chunk_attend repeats after each hop
        return ring_attention(q, k, v, causal=self.causal,
                              softmax_scale=self.scale)
