"""deepspeed_tpu — a TPU-native distributed training & inference framework
with DeepSpeed's capability surface.

Top-level API mirrors the reference `deepspeed/__init__.py`:
- `initialize()`        (reference :69)  → (engine, optimizer, dataloader, lr_scheduler)
- `init_inference()`    (reference :291) → InferenceEngine
- `init_distributed()`  (reference :43)
plus `zero`, `comm`, `ops`, `moe`, `sequence`, `pipe` sub-packages.
"""

from __future__ import annotations

import os

from typing import Any, Callable, Optional

__version__ = "0.1.0"

from deepspeed_tpu.utils import jax_compat  # noqa: F401  (installs shims)
from deepspeed_tpu.accelerator import get_accelerator  # noqa: F401
from deepspeed_tpu import comm  # noqa: F401
from deepspeed_tpu.comm.comm import init_distributed  # noqa: F401
from deepspeed_tpu.runtime.config import DeepSpeedConfig  # noqa: F401
from deepspeed_tpu.runtime.engine import DeepSpeedEngine  # noqa: F401
from deepspeed_tpu.utils import groups  # noqa: F401
from deepspeed_tpu.utils.groups import MeshTopology  # noqa: F401
from deepspeed_tpu.utils.logging import logger  # noqa: F401


def initialize(args=None,
               model: Any = None,
               optimizer=None,
               model_parameters: Any = None,
               training_data=None,
               lr_scheduler=None,
               distributed_port: int = 29500,
               mpu=None,
               mesh: Any = None,
               dist_init_required: Optional[bool] = None,
               collate_fn=None,
               config: Any = None,
               config_params: Any = None,
               loss_fn: Optional[Callable] = None,
               base_param_specs: Any = None,
               expert_param_fn: Optional[Callable] = None,
               topology: Optional[MeshTopology] = None):
    """Build a training engine from (model, config).

    Counterpart of reference `deepspeed/__init__.py:initialize:69`. `model` is
    a flax module (or anything whose loss is computed by `loss_fn(params,
    batch, rng)`), `model_parameters` the parameter pytree (host or device).
    The DP×SP×TP×EP×PP mesh is built from the config's parallel sizes
    (reference builds the DP×SP mesh at `__init__.py:155-163`), or adopt a
    caller-provided `mesh`/`topology`.
    """
    if config is None:
        config = config_params
    if dist_init_required is None or dist_init_required:
        init_distributed()

    # ---- autotuning intercept (reference launcher runner.py:390 →
    # Autotuner.tune:404): `ds_tpu --autotuning {tune,run}` or an enabled
    # {"autotuning": {...}} config block turns THIS initialize() call into
    # the tuning driver — short real trials over the candidate space,
    # results persisted/resumable, then exit (tune) or continue building
    # the engine with the winning config (run).
    from deepspeed_tpu.autotuning.driver import (autotuning_requested,
                                                 run_autotuning)
    _raw_for_at = config
    if isinstance(_raw_for_at, str):
        # only pay the parse when the CLI/env explicitly asked for
        # autotuning — path-config error semantics (DeepSpeedConfig's own
        # validation) stay untouched on the normal path
        if os.environ.get("DS_TPU_AUTOTUNING", "").strip().lower() in (
                "tune", "run") and os.path.isfile(_raw_for_at):
            import json as _json
            with open(_raw_for_at) as _f:
                _raw_for_at = _json.load(_f)
        else:
            _raw_for_at = None
    _at_mode = autotuning_requested(_raw_for_at)
    if _at_mode is not None:
        best, model, loss_fn = run_autotuning(
            model=model, model_parameters=model_parameters,
            raw_cfg=_raw_for_at if isinstance(_raw_for_at, dict) else {},
            loss_fn=loss_fn, base_param_specs=base_param_specs,
            mode=_at_mode, initialize_fn=initialize)
        if _at_mode == "tune":
            logger.info("autotuning: mode=tune — exiting after the sweep "
                        "(rerun with the written best.json, or use "
                        "mode=run to continue training immediately)")
            raise SystemExit(0)
        config = best  # mode=run: train with the winner (model rebuilt
        #                with winning model-side knobs by the driver)

    from deepspeed_tpu.pipe.module import PipelineModule
    pipeline_module = model if isinstance(model, PipelineModule) else None

    ds_config = config if isinstance(config, DeepSpeedConfig) else None
    if ds_config is None:
        # Parallel sizes must be known before batch triangulation.
        if topology is None:
            import json as _json
            raw = config
            if isinstance(config, str):
                with open(config) as f:
                    raw = _json.load(f)
            raw = raw or {}
            tp = int((raw.get("tensor_parallel", {}) or {}).get("tp_size", 1)) or 1
            sp = int(raw.get("sequence_parallel_size", 1))
            ep = int(raw.get("expert_parallel_size", 1))
            pp = int((raw.get("pipeline", {}) or {}).get("pipeline_parallel_size", 1))
            zero_raw = raw.get("zero_optimization", {}) or {}
            mics = int(zero_raw.get("mics_shard_size", 0) or 0)
            if mics <= 0:  # hpZ secondary partition rides the same axis split
                mics = int(zero_raw.get("zero_hpz_partition_size", 0) or 0)
                mics = mics if mics > 1 else 0
            if pipeline_module is not None and pipeline_module.num_stages:
                pp = pipeline_module.num_stages
            topology = MeshTopology(pp=pp, ep=ep, sp=sp, tp=tp, mesh=mesh,
                                    mics_shard_size=max(mics, 0))
        ds_config = DeepSpeedConfig(config, mpu=mpu,
                                    world_size=topology.world_size)
    elif topology is None:
        topology = MeshTopology(
            pp=ds_config.pipeline.pipeline_parallel_size,
            ep=ds_config.expert_parallel_size,
            sp=ds_config.sequence_parallel_size,
            tp=ds_config.tensor_parallel.tp_size,
            mesh=mesh)

    groups.initialize(topology)
    if pipeline_module is not None:
        n_stages = topology.pp_size
        if pipeline_module.num_stages not in (None, n_stages):
            raise ValueError(
                f"PipelineModule(num_stages={pipeline_module.num_stages}) != "
                f"mesh pipe size {n_stages}")
        if loss_fn is None:
            loss_fn = pipeline_module.build_loss_fn(
                ds_config.gradient_accumulation_steps, n_stages)
        if base_param_specs is None:
            base_param_specs = pipeline_module.param_specs()
    engine = DeepSpeedEngine(
        model=model, loss_fn=loss_fn, config=ds_config,
        model_parameters=model_parameters, base_param_specs=base_param_specs,
        topology=topology, training_data=training_data, collate_fn=collate_fn,
        lr_scheduler=lr_scheduler, optimizer=optimizer,
        expert_param_fn=expert_param_fn)
    return engine, engine.opt, engine.training_dataloader, engine.lr_scheduler


def init_inference(model: Any = None, config: Any = None, **kwargs):
    """Build an inference engine (reference deepspeed/__init__.py:init_inference:291).

    `model` is a zoo flax module or a `(module, params)` tuple; params may
    also be passed via the `params=` kwarg.
    """
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    params = kwargs.pop("params", None)
    if not isinstance(config, DeepSpeedInferenceConfig):
        config = DeepSpeedInferenceConfig(**{**(config or {}), **kwargs})
    return InferenceEngine(model, config, params=params)


def add_config_arguments(parser):
    """Reference deepspeed/__init__.py:268 — CLI arg injection."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true")
    group.add_argument("--deepspeed_config", default=None, type=str)
    group.add_argument("--deepscale", default=False, action="store_true")
    group.add_argument("--local_rank", type=int, default=-1)
    return parser
