from deepspeed_tpu.op_builder.builder import (  # noqa: F401
    ALL_OPS, AsyncIOBuilder, FlashAttentionBuilder, FusedAdamBuilder,
    OpBuilder, QuantizerBuilder, get_op_builder)
