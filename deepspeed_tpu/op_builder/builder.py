"""Op builder registry (reference `op_builder/builder.py`: `OpBuilder:109`,
`jit_load:533`, `op_builder/all_ops.py`).

Two kinds of "ops" exist on TPU:
- **Pallas/XLA ops** (flash attention, fused optimizers, quantization):
  compiled by XLA at trace time — `load()` simply returns the python module
  exposing them (`is_compatible` reports where the fast path runs).
- **Native host ops** (async NVMe I/O): real C++ JIT-compiled with g++ into
  a shared library on first `load()` and cached under ~/.cache — the
  `jit_load` flow, with ctypes instead of pybind11.
"""

from __future__ import annotations

import ctypes
import hashlib
import importlib
import os
import subprocess
from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class OpBuilder:
    BUILD_VAR = "DS_BUILD_OPS"
    NAME = "op"

    def is_compatible(self, verbose: bool = False) -> bool:
        return True

    def load(self, verbose: bool = False):
        raise NotImplementedError

    # ---- native JIT machinery (reference jit_load:533) ----
    def jit_load_ctypes(self, sources, extra_flags=()) -> ctypes.CDLL:
        src_paths = [os.path.join(_REPO_ROOT, s) for s in sources]
        blob = b"".join(open(p, "rb").read() for p in src_paths)
        tag = hashlib.sha1(blob).hexdigest()[:12]
        cache = os.environ.get("DS_TPU_OP_CACHE",
                               os.path.expanduser("~/.cache/deepspeed_tpu/ops"))
        os.makedirs(cache, exist_ok=True)
        so_path = os.path.join(cache, f"{self.NAME}_{tag}.so")
        if not os.path.exists(so_path):
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
                   *extra_flags, *src_paths, "-o", so_path]
            logger.info(f"op_builder: compiling {self.NAME}: {' '.join(cmd)}")
            subprocess.run(cmd, check=True, capture_output=True)
        return ctypes.CDLL(so_path)


class _PythonOpBuilder(OpBuilder):
    """Pallas/XLA-backed op: load() returns the implementing module."""
    MODULE = ""

    def load(self, verbose: bool = False):
        return importlib.import_module(self.MODULE)


class FusedAdamBuilder(_PythonOpBuilder):
    NAME = "fused_adam"
    MODULE = "deepspeed_tpu.ops.optimizers"


class FusedLambBuilder(_PythonOpBuilder):
    NAME = "fused_lamb"
    MODULE = "deepspeed_tpu.ops.optimizers"


class CPUAdamBuilder(_PythonOpBuilder):
    # host-compute Adam (compute_on('device_host')) — engine wires it
    NAME = "cpu_adam"
    MODULE = "deepspeed_tpu.ops.optimizers"


class FlashAttentionBuilder(_PythonOpBuilder):
    NAME = "flash_attn"
    MODULE = "deepspeed_tpu.ops.pallas.flash_attention"

    def is_compatible(self, verbose: bool = False) -> bool:
        try:
            import jax
            return jax.devices()[0].platform in ("tpu", "axon")
        except Exception:
            return False


class QuantizerBuilder(_PythonOpBuilder):
    NAME = "quantizer"
    MODULE = "deepspeed_tpu.ops.quantization"


class TransformerBuilder(_PythonOpBuilder):
    NAME = "transformer"
    MODULE = "deepspeed_tpu.ops.attention"


class InferenceCoreBuilder(_PythonOpBuilder):
    NAME = "inference_core_ops"
    MODULE = "deepspeed_tpu.inference.kv_cache"


class AsyncIOBuilder(OpBuilder):
    """Native async file I/O (reference op_builder/async_io.py + csrc/aio)."""
    NAME = "async_io"
    SOURCES = ["csrc/aio/ds_aio.cpp"]

    def is_compatible(self, verbose: bool = False) -> bool:
        from shutil import which
        return which("g++") is not None

    def load(self, verbose: bool = False):
        lib = self.jit_load_ctypes(self.SOURCES)
        lib.ds_aio_create.restype = ctypes.c_void_p
        lib.ds_aio_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.ds_aio_destroy.argtypes = [ctypes.c_void_p]
        lib.ds_aio_open.restype = ctypes.c_int
        lib.ds_aio_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.ds_aio_close.argtypes = [ctypes.c_int]
        for fn in (lib.ds_aio_pread, lib.ds_aio_pwrite):
            fn.restype = ctypes.c_longlong
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_longlong, ctypes.c_longlong]
        lib.ds_aio_wait.restype = ctypes.c_longlong
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p]
        lib.ds_aio_create_ex.restype = ctypes.c_void_p
        lib.ds_aio_create_ex.argtypes = [ctypes.c_int, ctypes.c_int,
                                         ctypes.c_longlong]
        lib.ds_aio_using_uring.restype = ctypes.c_int
        lib.ds_aio_using_uring.argtypes = [ctypes.c_void_p]
        return lib


ALL_OPS: Dict[str, Any] = {
    b.NAME: b for b in (FusedAdamBuilder, FusedLambBuilder, CPUAdamBuilder,
                        FlashAttentionBuilder, QuantizerBuilder,
                        TransformerBuilder, InferenceCoreBuilder,
                        AsyncIOBuilder)
}


def get_op_builder(name: str) -> OpBuilder:
    """Reference accelerator `get_op_builder` surface."""
    return ALL_OPS[name]()
