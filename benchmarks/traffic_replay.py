"""Fault-composable traffic replay against the v2 serving engine.

Drives `InferenceEngineV2` with an open-loop request stream — Poisson
arrivals, weighted prompt/output-length mixes, a shared-prefix pool — and
asserts the request-span tracing contract end to end:

  - ZERO dropped requests: every submitted uid finishes with a
    `request_span` summary (faults retried at the put() boundary — the
    engine's `generate_dispatch` fault point fires BEFORE any admission
    mutation, so a retry sees clean state);
  - stall accounting: per-request `unattributed_frac` stays under
    `--max-unattributed` (default 1%) — in put mode the harness wraps each
    scheduling round in a depth-0 `round` span, so fault stalls and retry
    backoff inside the round attribute instead of leaking;
  - resilience instants 1:1: every fault/retry/watchdog/degrade event the
    hub saw during the replay is mirrored in the tracer's `instants`;
  - the Chrome-trace export parses and is monotonic (ts/dur >= 0).

Runnable with a fault schedule mid-flight:

  DS_TPU_FAULTS="generate_dispatch/v2_put:raise@3,7" \\
      python benchmarks/traffic_replay.py --n-requests 8

Two drive modes: `--api put` (default; the harness IS the serving loop —
continuous batching via put(argmax_only=True), per-arrival admission) and
`--api generate` (one engine.generate() call over the whole stream; the
engine's own loop provides the admit/decode_wave/mixed_round
decomposition and the OOM degrade ladder — compose with
DS_TPU_FAULTS="program_compile/<mode>:oom@1" and `--floor` to assert a
degraded-mode throughput floor).

Prints ONE JSON summary line; exit code 1 when any assertion failed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_mix(spec: str):
    """'12:2,24:1' → ([12, 24], [2/3, 1/3])."""
    lens, weights = [], []
    for part in spec.split(","):
        n, _, w = part.partition(":")
        lens.append(int(n))
        weights.append(float(w) if w else 1.0)
    total = sum(weights)
    return lens, [w / total for w in weights]


def build_workload(args, vocab: int, rng: np.random.Generator):
    """The replay script: (uid, arrival_s, prompt ndarray, out_target)."""
    plens, pw = _parse_mix(args.prompt_mix)
    olens, ow = _parse_mix(args.out_mix)
    # shared-prefix pool: block-aligned length so paged prefix matching can
    # commit full blocks (partial tails never register)
    pool = [rng.integers(0, vocab, args.prefix_len).astype(np.int32)
            for _ in range(max(1, args.prefix_pool))]
    t, reqs = 0.0, []
    for i in range(args.n_requests):
        t += float(rng.exponential(1.0 / args.rate))
        plen = int(rng.choice(plens, p=pw))
        out = int(rng.choice(olens, p=ow))
        tail = rng.integers(0, vocab, plen).astype(np.int32)
        if args.prefix_share > 0 and rng.random() < args.prefix_share:
            pre = pool[int(rng.integers(0, len(pool)))]
            prompt = np.concatenate([pre, tail])
        else:
            prompt = tail
        reqs.append((i, t, prompt, out))
    return reqs


def replay_put(engine, reqs, args):
    """Open-loop continuous batching through put(argmax_only=True). The
    harness is the serving loop, so it owns the depth-0 `round` span (put's
    prefill/chunk/decode spans nest inside it and still export to the
    Chrome trace) and the first-token stamps."""
    from deepspeed_tpu.resilience.retry import retry_call

    tr = engine.tracer
    pending = list(reqs)           # arrival-ordered
    live = {}                      # uid -> [produced, target, last_token]
    draining = set()               # admitted, prefill not finished
    produced_total = 0
    t0 = time.perf_counter()
    trace_t0 = tr.now()            # arrival_s → tracer timeline offset
    t_first = None

    while pending or live or draining:
        now = time.perf_counter() - t0
        feeds_u, feeds_t = [], []
        # admit due arrivals while slots are free
        while pending and pending[0][1] <= now and \
                len(live) + len(draining) < engine.max_batch:
            uid, arr, prompt, out = pending.pop(0)
            tr.begin_request(uid, prompt_tokens=len(prompt),
                             submit_s=trace_t0 + arr)
            feeds_u.append(uid)
            feeds_t.append(prompt)
            draining.add(uid)
            live[uid] = [0, out, None]
        for uid, st in live.items():
            if st[2] is not None:          # has a token to feed back
                feeds_u.append(uid)
                feeds_t.append(np.asarray([st[2]], np.int32))
                st[2] = None
        if not feeds_u and not draining:
            # idle: no live work, next arrival in the future
            if pending:
                time.sleep(max(0.0, pending[0][1]
                               - (time.perf_counter() - t0)))
            continue
        with tr.span("round", uids=tuple(live)):
            out = retry_call(
                lambda: engine.put(feeds_u, feeds_t, argmax_only=True),
                what="traffic_replay_put", retries=args.retries,
                base_delay=0.01)
            if t_first is None:
                t_first = time.perf_counter()
            for uid, tok in out.items():
                st = live.get(uid)
                if st is None:
                    continue
                tok = int(np.asarray(tok).reshape(-1)[-1])
                if st[0] == 0:
                    tr.first_token(uid)
                draining.discard(uid)
                st[0] += 1
                produced_total += 1
                st[2] = tok
        done = [uid for uid, st in live.items() if st[0] >= st[1]]
        if done:
            engine._flush_batch(done)      # ends the request traces
            for uid in done:
                del live[uid]
    dt = (time.perf_counter() - (t_first or t0))
    return produced_total, dt


def replay_generate(engine, reqs, args):
    """One generate() call over the stream — the engine's own continuous-
    batching loop provides the span decomposition and the degrade ladder."""
    prompts = [list(map(int, p)) for _, _, p, _ in reqs]
    max_new = max(out for _, _, _, out in reqs)
    t0 = time.perf_counter()
    outs = engine.generate(prompts, max_new_tokens=max_new)
    dt = time.perf_counter() - t0
    return sum(max(0, len(o) - len(p)) for o, p in zip(outs, prompts)), dt


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--n-requests", type=int, default=12)
    ap.add_argument("--rate", type=float, default=20.0,
                    help="Poisson arrival rate, requests/s")
    ap.add_argument("--prompt-mix", default="12:2,24:1",
                    help="len:weight[,len:weight...]")
    ap.add_argument("--out-mix", default="4:2,8:1")
    ap.add_argument("--prefix-share", type=float, default=0.5,
                    help="fraction of prompts drawing a pooled prefix")
    ap.add_argument("--prefix-pool", type=int, default=2)
    ap.add_argument("--prefix-len", type=int, default=16)
    ap.add_argument("--api", choices=("put", "generate"), default="put")
    ap.add_argument("--serve-mode", default=None,
                    help="dequant | layer_scan | capacity (streamed modes "
                         "quantize the tree and force the slot KV layout)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--retries", type=int, default=4,
                    help="put-round retry budget (absorbs raise faults)")
    ap.add_argument("--max-unattributed", type=float, default=0.01)
    ap.add_argument("--floor", type=float, default=None,
                    help="assert decode throughput >= FLOOR tok/s "
                         "(degraded-mode acceptance)")
    ap.add_argument("--jsonl", default="traffic_replay.jsonl")
    ap.add_argument("--export-trace", metavar="OUT", default=None)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models.llama import LlamaConfig, materialize_params
    from deepspeed_tpu.resilience.faults import faults_active
    from deepspeed_tpu.telemetry import hub as hub_mod
    from deepspeed_tpu.telemetry.spans import INSTANT_KINDS, \
        export_chrome_trace
    from deepspeed_tpu.utils import groups

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=4096, num_hidden_layers=24,
                          num_attention_heads=8, num_key_value_heads=8,
                          max_position_embeddings=2048, remat=False,
                          dtype=jnp.bfloat16)
        mb, msl = 16, 1024
    else:
        cfg = LlamaConfig(vocab_size=1024, hidden_size=128,
                          intermediate_size=256, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256, remat=False,
                          dtype=jnp.float32)
        mb, msl = 4, 128

    try:
        os.remove(args.jsonl)
    except OSError:
        pass
    hub = hub_mod.TelemetryHub(enabled=True, jsonl_path=args.jsonl)
    hub_mod.set_hub(hub)
    # count resilience instants independently of the tracer's mirror — the
    # 1:1 assertion compares the two tallies over the same event stream
    fired = {}

    def _count(rec):
        if rec.get("kind") in INSTANT_KINDS:
            fired[rec["kind"]] = fired.get(rec["kind"], 0) + 1
    hub_mod.add_listener(_count)

    rng = np.random.default_rng(args.seed)
    groups.reset_topology()
    model, params = materialize_params(cfg)
    kw = dict(max_batch=mb, max_seq_len=msl, split_fuse_chunk=16,
              cache_block_size=args.prefix_len)
    if args.serve_mode not in (None, "dequant"):
        kw.update(quant={"enabled": True})
    if args.serve_mode is not None:
        kw.update(serve_mode=args.serve_mode)
    engine = InferenceEngineV2(model, params=params, **kw)
    engine.tracer.attach()

    reqs = build_workload(args, cfg.vocab_size, rng)
    if args.api == "put":
        produced, dt = replay_put(engine, reqs, args)
    else:
        produced, dt = replay_generate(engine, reqs, args)
    for hname in ("ttft_s", "tpot_s", "e2e_s"):
        hub.histogram_event(hname)

    tr = engine.tracer
    failures = []
    finished = {s["uid"]: s for s in tr.last_requests.values()}
    dropped = [uid for uid, _, _, _ in reqs if uid not in finished]
    if dropped:
        failures.append(f"dropped requests: {dropped}")
    worst_unattr = max((s["unattributed_frac"]
                        for s in finished.values()), default=0.0)
    if worst_unattr > args.max_unattributed:
        worst = max(finished.values(),
                    key=lambda s: s["unattributed_frac"])
        failures.append(
            f"unattributed_frac {worst_unattr:.4f} > "
            f"{args.max_unattributed} (uid {worst['uid']}, "
            f"spans {worst['spans']})")
    mirrored = {}
    for inst in tr.instants:
        mirrored[inst["kind"]] = mirrored.get(inst["kind"], 0) + 1
    if mirrored != fired:
        failures.append(f"instant mirror mismatch: hub saw {fired}, "
                        f"tracer mirrored {mirrored}")
    tok_s = produced / dt if dt > 0 else 0.0
    if args.floor is not None and tok_s < args.floor:
        failures.append(f"throughput {tok_s:.1f} tok/s under floor "
                        f"{args.floor}")
    if args.export_trace:
        from deepspeed_tpu.telemetry.__main__ import load_events
        trace = export_chrome_trace(load_events(args.jsonl),
                                    path=args.export_trace)
        bad = [e for e in trace["traceEvents"]
               if e.get("ts", 0) < 0 or e.get("dur", 0) < 0]
        if bad:
            failures.append(f"non-monotonic trace events: {bad[:3]}")
        json.loads(open(args.export_trace).read())  # parses back

    ttfts = sorted(s["ttft_s"] for s in finished.values()
                   if s.get("ttft_s") is not None)
    pct = lambda a, q: a[min(len(a) - 1, int(q * len(a)))] if a else None
    print(json.dumps({
        "harness": "traffic_replay", "api": args.api,
        "serve_mode": engine.serve_mode, "requests": len(reqs),
        "finished": len(finished), "dropped": len(dropped),
        "decode_tok_s": round(tok_s, 1),
        "ttft_p50_ms": round(pct(ttfts, 0.5) * 1e3, 1) if ttfts else None,
        "ttft_p99_ms": round(pct(ttfts, 0.99) * 1e3, 1) if ttfts else None,
        "unattributed_frac_max": round(worst_unattr, 4),
        "faults_active": faults_active(), "instants": fired,
        "spans_recorded": tr.spans_recorded,
        "ok": not failures, "failures": failures}))
    hub_mod.remove_listener(_count)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
