"""7B-class HF checkpoint → v5e decode (VERDICT r4 missing #1).

The reference fork's own harnesses serve real 7-13B models
(`/root/reference/zero.py:38-60` Qwen-3B ZeRO-offload inference,
`/root/reference/benchmark.py:181-292` kernel-injected 7-13B). This box
has zero egress, so no real weights exist locally; the at-scale claims
this harness DOES validate with a synthesized llama-2-7b checkpoint in
the real HF on-disk format (sharded fp16 safetensors + index json,
exactly what `load_state_dict` walks):

  1. the converter at real scale: 6.7B params through `_convert_llama`'s
     stack/transpose path and bf16 device placement (~12.6 GB HBM);
  2. KV-cache greedy decode throughput of the v1 engine at 7B — rides
     the engine's AUTO-layout path (r5): without it XLA copies the
     q/k/v stacks to its preferred tiling in-program (+3 GB, OOM);
  3. the int8 ZeRO-Inference path at scale, END-TO-END through the
     engine (checkpoint → converter → engine quantization → serve):
     with `quant={"enabled": True}` the serve-mode selector picks
     `quantized_layer_scan` at 7B (the whole-tree dequant residency
     would crowd HBM), the engine quantizes the layer stacks per layer
     on device, and generate scans them with the fused dequant-GEMM
     kernel (docs/quantized_serving.md).

MEASURED (r5, 1×v5e): load 6.74 B params in ~9 min (disk-bound);
bf16 decode 162 tok/s @ b4 (~16.5 ms/step — the 13.5 GB/step weight
read is the bound, ~80% of HBM bandwidth); int8 whole-tree dequant
RESOURCE_EXHAUSTED as predicted — which is why the engine now serves
7B int8 via the layer scan (int8 reads 6.84 GB/step vs 13.21 dense —
the fused kernel makes that a throughput WIN, not just capacity;
r6 on-chip numbers pend the next TPU-attached run).

CAPACITY mode (r7): `--capacity` serves the same checkpoint with the
layers parked in HOST memory and streamed per layer with double-buffered
`jax.device_put` prefetch (`inference/capacity_scan.py`) — the engine
lift of the r5 `capacity_serve.py` probe's (b) outcome: XLA refuses to
auto-stage pinned_host params into compute ("memory_space of all inputs
passed to `gather` must be the same"), so staging must be an explicit
per-layer transfer. At 7B this bounds HBM to ~2 layer slices (~0.4 GB
bf16 / ~0.2 GB int8) + KV + workspace instead of the 12.6 GB resident
tree; decode becomes PCIe-bound (~13.5 GB/step bf16 over the wire,
~6.8 GB/step with --int8 — int8 halves PCIe traffic exactly as it
halves HBM reads). Expect capacity decode well BELOW the resident
162 tok/s — the mode's point is serving trees that can't be resident
at all (docs/capacity_serving.md has the throughput model).

SPECULATIVE decoding (r8): `--spec` layers k-token draft-and-verify
(docs/speculative_decoding.md) over whichever serve mode the other
flags select — greedy, so the output chain is bit-exact vs the
non-spec run and tok/s is directly comparable. The self-draft is a
half-depth layer slice sharing the checkpoint (no second model on
disk); each target weight pass — HBM read resident, PCIe stream under
--capacity — then emits `acceptance·k + 1` tokens instead of 1, which
is the weight-read-bound breaker at exactly these 7B shapes. Rows gain
`acceptance_rate` (the tiled synthetic checkpoint accepts unusually
well — real-weights acceptance is the number that matters on chip).

INT8 KV (r8, `--kv-int8`): the cache itself goes int8-at-rest
(`kv_cache_dtype='int8'`, docs/kv_cache.md) — per-(kv-head, slot) f32
scales, dequantized in-register by the attention kernels. At 7B/4k the
KV pool halves (the `model_kv_budget` max-batch doubler); at this
harness's b4/s96 shapes the win is bytes, not tok/s (weights dominate
the step read). Composes with --spec (greedy spec stays bit-exact vs
the non-spec run AT THE SAME kv dtype); under --int8/--capacity the
streamed modes keep dense KV and the engine warns (rows record the
effective kv dtype).

Usage: python benchmarks/hf7b_decode.py [ckpt_dir] [--int8]
[--capacity] [--spec] [--kv-int8] (default dir /tmp/llama7b-synth;
synthesized on first run, ~13 GB on disk. --int8 skips the bf16 phase
and runs only the engine-integrated quantized_layer_scan serve path;
--capacity streams host-parked layers instead of resident serving, and
combines with --int8 for the int8-over-PCIe variant; --spec and
--kv-int8 compose with both)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CFG = dict(model_type="llama", vocab_size=32000, hidden_size=4096,
           intermediate_size=11008, num_hidden_layers=32,
           num_attention_heads=32, num_key_value_heads=32,
           max_position_embeddings=4096, rope_theta=10000.0,
           rms_norm_eps=1e-5, tie_word_embeddings=False,
           torch_dtype="float16")


def synthesize(path: str) -> None:
    """Write a llama-2-7b-shaped checkpoint: fp16 sharded safetensors +
    index, 4 layers per shard. Values tile a random block — realistic
    per-block statistics for the int8 quantizer without minutes of RNG."""
    from safetensors.numpy import save_file
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(0)
    tile = (rng.standard_normal(1 << 20).astype(np.float16) * 0.02)

    def mat(shape):
        n = int(np.prod(shape))
        reps = -(-n // tile.size)
        return np.tile(tile, reps)[:n].reshape(shape)

    d, f, L = CFG["hidden_size"], CFG["intermediate_size"], CFG["num_hidden_layers"]
    weight_map = {}
    shard_id = 0

    def write(shard, tensors):
        nonlocal shard_id
        name = f"model-{shard_id:05d}.safetensors"
        save_file(tensors, os.path.join(path, name))
        for k in tensors:
            weight_map[k] = name
        shard_id += 1

    write(0, {"model.embed_tokens.weight": mat((CFG["vocab_size"], d)),
              "model.norm.weight": np.ones((d,), np.float16),
              "lm_head.weight": mat((CFG["vocab_size"], d))})
    for base in range(0, L, 4):
        tensors = {}
        for i in range(base, min(base + 4, L)):
            p = f"model.layers.{i}."
            for proj in ("q_proj", "k_proj", "v_proj", "o_proj"):
                tensors[f"{p}self_attn.{proj}.weight"] = mat((d, d))
            tensors[f"{p}mlp.gate_proj.weight"] = mat((f, d))
            tensors[f"{p}mlp.up_proj.weight"] = mat((f, d))
            tensors[f"{p}mlp.down_proj.weight"] = mat((d, f))
            tensors[f"{p}input_layernorm.weight"] = np.ones((d,), np.float16)
            tensors[f"{p}post_attention_layernorm.weight"] = \
                np.ones((d,), np.float16)
        write(0, tensors)
    with open(os.path.join(path, "model.safetensors.index.json"), "w") as fh:
        json.dump({"metadata": {}, "weight_map": weight_map}, fh)
    with open(os.path.join(path, "config.json"), "w") as fh:
        json.dump(CFG, fh)


def main():
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.module_inject import load_hf_checkpoint
    from deepspeed_tpu.utils import groups

    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    int8_only = "--int8" in sys.argv[1:]
    capacity = "--capacity" in sys.argv[1:]
    # --spec: k-token draft-and-verify over the selected serve mode
    # (greedy → bit-exact, tok/s directly comparable to the plain run)
    spec_cfg = ({"enabled": True, "k": 4}
                if "--spec" in sys.argv[1:] else None)
    # --kv-int8: int8-at-rest KV cache (dequant serve mode; the streamed
    # modes warn and keep dense KV — rows record the effective dtype)
    kv_int8 = "--kv-int8" in sys.argv[1:]
    kv_kw = {"kv_cache_dtype": "int8"} if kv_int8 else {}

    def _kv_dtype(eng):
        return ("int8" if kv_int8 and eng.serve_mode == "dequant"
                else "bf16")

    def _acc(eng):
        s = getattr(eng, "_spec", None)
        return (round(s.last_acceptance_rate, 4)
                if s is not None and s.last_acceptance_rate is not None
                else None)

    def _residency():
        # registered MemoryPlane residency per tier (nonzero tiers only) —
        # the formula/ledger number the on-chip memory_stats() reconcile
        # compares against (docs/memory.md)
        from deepspeed_tpu.telemetry.memory import get_plane
        return {t: b for t, b in get_plane().tier_totals().items() if b}
    path = args[0] if args else "/tmp/llama7b-synth"
    if not os.path.exists(os.path.join(path, "model.safetensors.index.json")):
        t0 = time.time()
        synthesize(path)
        print(json.dumps({"synthesized": path,
                          "seconds": round(time.time() - t0, 1)}))

    import jax.tree_util as jtu

    groups.reset_topology()
    t0 = time.time()
    # load HOST-side (the converter's stack/transpose at real scale);
    # device placement is staged per phase below
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        model, hparams = load_hf_checkpoint(path, dtype=jnp.bfloat16,
                                            param_dtype=jnp.bfloat16)
    n = sum(v.size for v in jtu.tree_leaves(hparams))
    load_s = time.time() - t0
    print(json.dumps({"loaded_params_b": round(n / 1e9, 2),
                      "load_seconds": round(load_s, 1)}), flush=True)

    tpu = jax.devices()[0]
    b, prompt, new = 4, 64, 32
    ids = np.random.default_rng(1).integers(0, CFG["vocab_size"], (b, prompt))

    # ---- capacity mode (--capacity [--int8]): layers stay HOST-parked
    # (numpy tier, quantized per layer under --int8) and stream through
    # the double-buffered per-layer device_put loop — HBM holds only
    # embed/norm/head + ~2 layer slices + KV + workspace. The engine owns
    # the only param reference, same as the resident phases.
    if capacity:
        try:
            t0 = time.time()
            eng = deepspeed_tpu.init_inference(
                model, params=hparams, dtype="bf16", serve_mode="capacity",
                quant={"enabled": True} if int8_only else None,
                speculative=spec_cfg, **kv_kw)
            del hparams
            stage_s = time.time() - t0
            r = eng._capacity
            print(json.dumps({"capacity_mode": {
                "int8": int8_only, "stage_s": round(stage_s, 1),
                "h2d_gb_step": round(r.h2d_bytes_pass() / 1e9, 2),
                "planned_peak_gb": round(r.plan.peak_hbm_bytes / 1e9, 2),
                "host_resident": r.host_resident()}}), flush=True)
            t0 = time.time()
            out = eng.generate(ids, max_new_tokens=new)
            compile_s = time.time() - t0
            t0 = time.time()
            out = eng.generate(ids, max_new_tokens=new)
            dt = time.time() - t0
            toks = np.asarray(out)[:, prompt:]
            print(json.dumps({"capacity_decode": {
                "int8": int8_only, "spec": spec_cfg is not None,
                "kv_dtype": _kv_dtype(eng),
                "acceptance_rate": _acc(eng),
                "decode_tokens_per_sec": round(b * new / dt, 1),
                "compile_s": round(compile_s, 1),
                "prefetch_stall_ms": round(r.last_prefetch_stall_ms, 1),
                "registered_bytes_by_tier": _residency(),
                "distinct_tokens": int(len(np.unique(toks)))}}), flush=True)
        except Exception as e:
            print(json.dumps({"capacity_decode": {
                "error": str(e)[:160].replace("\n", " ")}}), flush=True)
        return

    # ---- bf16 greedy decode (12.6 GB of weights on HBM). The engine
    # gets the HOST tree and owns the only device reference — its
    # AUTO-layout relayout frees each default-layout leaf as it re-places
    # it, which a second caller-held reference would defeat (13.5 GB × 2).
    eng = None
    try:
        if int8_only:
            raise RuntimeError("skipped (--int8)")
        t0 = time.time()
        eng = deepspeed_tpu.init_inference(model, params=hparams,
                                           dtype="bf16",
                                           speculative=spec_cfg, **kv_kw)
        h2d_s = time.time() - t0
        t0 = time.time()
        out = eng.generate(ids, max_new_tokens=new)   # compile + relayout
        compile_s = time.time() - t0
        t0 = time.time()
        out = eng.generate(ids, max_new_tokens=new)
        dt = time.time() - t0
        toks = np.asarray(out)[:, prompt:]
        row = {"model": "llama7b-synth bf16", "batch": b,
               "spec": spec_cfg is not None, "kv_dtype": _kv_dtype(eng),
               "acceptance_rate": _acc(eng),
               "decode_tokens_per_sec": round(b * new / dt, 1),
               "h2d_s": round(h2d_s, 1), "compile_s": round(compile_s, 1),
               "registered_bytes_by_tier": _residency(),
               "distinct_tokens": int(len(np.unique(toks)))}
        print(json.dumps({"bf16_decode": row}), flush=True)
    except Exception as e:
        print(json.dumps({"bf16_decode": {
            "error": str(e)[:160].replace("\n", " ")}}), flush=True)
    finally:
        if eng is not None:
            eng.params = None
            eng.cache = None
        del eng
        import gc
        gc.collect()

    # ---- int8, engine-integrated (the r6 quantized_layer_scan path):
    # the engine places the bf16 tree, quantizes the layer stacks PER
    # LAYER on device (leaf-wise rebinding — peak HBM ≈ bf16 tree + one
    # int8 leaf, falling to the 7.1 GB int8 tree as bf16 leaves free),
    # and generate runs the layer scan with the fused dequant-GEMM
    # kernel. serve_mode='auto' must pick layer_scan at this size.
    eng = None
    try:
        t0 = time.time()
        eng = deepspeed_tpu.init_inference(
            model, params=hparams, dtype="bf16", quant={"enabled": True},
            speculative=spec_cfg, **kv_kw)
        q_s = time.time() - t0
        del hparams  # the engine owns the only reference (see bf16 note)
        wb, wb_dense = eng._weight_bytes_per_step()
        print(json.dumps({"int8_serve_mode": eng.serve_mode,
                          "quantize_s": round(q_s, 1),
                          "weight_gb_step_int8": round(wb / 1e9, 2),
                          "weight_gb_step_dense": round(wb_dense / 1e9, 2)}),
              flush=True)
        t0 = time.time()
        out = eng.generate(ids, max_new_tokens=new)
        compile_s = time.time() - t0
        t0 = time.time()
        out = eng.generate(ids, max_new_tokens=new)
        dt = time.time() - t0
        toks = np.asarray(out)[:, prompt:]
        print(json.dumps({"int8_decode": {
            "serve_mode": eng.serve_mode,
            "spec": spec_cfg is not None, "kv_dtype": _kv_dtype(eng),
            "acceptance_rate": _acc(eng),
            "decode_tokens_per_sec": round(b * new / dt, 1),
            "compile_s": round(compile_s, 1),
            "registered_bytes_by_tier": _residency(),
            "distinct_tokens": int(len(np.unique(toks)))}}), flush=True)
    except Exception as e:
        print(json.dumps({"int8_decode": {
            "error": str(e)[:160].replace("\n", " ")}}), flush=True)
    finally:
        if eng is not None:
            eng.params = None
        del eng


if __name__ == "__main__":
    main()
