"""ZeRO-Inference capacity serving — engine-path harness + A/B
(reference `deepspeed/inference/` ZeRO-Inference: weights live on CPU/NVMe
and stream to the accelerator per layer, trading bandwidth for capacity —
the path that serves models LARGER than device memory; Aminabadi et al.
2022, Rajbhandari et al. 2021).

HISTORY — the r5 PROBE this harness grew from measured outcome (b) on
1×v5e: with params truly placed `pinned_host`, the first gather fails to
compile ("memory_space of all inputs passed to `gather` must be the
same") — XLA does NOT auto-stage host operands into compute, and even
slicing a host-memory-space Array enters compute with a host operand. A
TPU capacity mode therefore needs an EXPLICIT per-layer `jax.device_put`
inside a host-driven layer loop. That engine now exists
(`inference/capacity_scan.py`, `serve_mode="capacity"`): host-parked
per-layer numpy slices, double-buffered H2D prefetch (layer l+1's
transfer dispatched while layer l's block computes), optional int8 via
the per-layer quantizer (halves PCIe bytes; fused dequant-GEMM consumes
int8 directly) and an NVMe tier on the striped aio engine.

Phases (run on the real chip; CPU-mesh runs are functional proxies only —
host→device "transfers" are memcpys, so overlap ratios there understate
the chip):

  serve  — capacity-mode decode via the ENGINE: tok/s, per-step H2D
           bytes, prefetch stall, host-residency check
  ab     — the acceptance A/B: double-buffered prefetch vs synchronous
           stage-then-compute staging (`capacity={"double_buffer":
           False}`), same process, best-of-3 — target ≥1.3x on chip
  nvme   — half the layers parked on NVMe through the aio engine
  probe  — the legacy r5 pinned_host placement probe (kept for reference;
           expected to FAIL compile with the gather memory_space error)

Usage: python benchmarks/capacity_serve.py [small|7b] [serve|ab|nvme|probe]
       [--int8]  (defaults: small serve)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cfg(big: bool):
    import jax.numpy as jnp
    from deepspeed_tpu.models.llama import LlamaConfig
    if big:
        return LlamaConfig(vocab_size=32000, hidden_size=4096,
                           intermediate_size=11008, num_hidden_layers=32,
                           num_attention_heads=32, num_key_value_heads=32,
                           max_position_embeddings=4096, remat=False,
                           dtype=jnp.bfloat16)
    return LlamaConfig(vocab_size=32000, hidden_size=1024,
                       intermediate_size=4096, num_hidden_layers=24,
                       num_attention_heads=8, num_key_value_heads=8,
                       max_position_embeddings=2048, remat=False,
                       dtype=jnp.bfloat16)


def _host_params(model):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))
        raw, _ = extract_params_and_specs(variables)
        return jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), raw)


def _timed_decode(eng, ids, new, iters=3):
    """Best-of-N generate wall time (generate fetches its output — a real
    materialization, trustworthy through the axon tunnel)."""
    eng.generate(ids, max_new_tokens=new)  # compile + warm transfers
    best = float("inf")
    for _ in range(iters):
        t0 = time.time()
        eng.generate(ids, max_new_tokens=new)
        best = min(best, time.time() - t0)
    return best


def main():
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaForCausalLM
    from deepspeed_tpu.utils import groups

    args = sys.argv[1:]
    big = "7b" in args
    int8 = "--int8" in args
    phase = next((a for a in args if a in ("serve", "ab", "nvme", "probe")),
                 "serve")

    # Program ledger: the capacity block program (and per-key generate
    # measured rows) land in a JSONL for round-over-round diffing, and the
    # CapacityPlan is checked against the compiled block's memory_analysis
    from deepspeed_tpu.telemetry import ledger as ledger_mod
    ledger_path = os.environ.get("DS_TPU_LEDGER_JSONL",
                                 "ledger_capacity.jsonl")
    ledger_mod.set_ledger(
        ledger_mod.ProgramLedger(path=ledger_path, enabled=True))
    cfg = _cfg(big)
    model = LlamaForCausalLM(cfg)
    params = _host_params(model)
    print(json.dumps({"phase": phase, "model": "7b" if big else "small",
                      "int8": int8, "params_gb": round(sum(
                          v.nbytes for v in jax.tree_util.tree_leaves(params))
                          / 1e9, 2),
                      "platform": jax.devices()[0].platform}), flush=True)
    b, s, new = 4, 64, 16
    ids = np.random.default_rng(1).integers(0, 32000, (b, s))
    quant = {"enabled": True} if int8 else None

    def capacity_engine(**capacity_opts):
        groups.reset_topology()
        return deepspeed_tpu.init_inference(
            model, params=params, dtype="bf16", serve_mode="capacity",
            quant=quant, capacity=capacity_opts or None)

    if phase == "serve":
        eng = capacity_engine()
        r = eng._capacity
        dt = _timed_decode(eng, ids, new)
        print(json.dumps({"capacity_decode": {
            "tokens_per_sec": round(b * new / dt, 1),
            "h2d_gb_step": round(r.last_h2d_bytes_step / 1e9, 3),
            "prefetch_stall_ms_total": round(r.last_prefetch_stall_ms, 1),
            "host_resident": r.host_resident(),
            "planned_peak_gb": round(r.plan.peak_hbm_bytes / 1e9, 2),
            "plan_vs_compiled_ok": r.check_plan(),
            "ledger": ledger_path}}),
            flush=True)

    elif phase == "ab":
        # the acceptance A/B: one process, same weights, best-of-3 each.
        # Synchronous staging FIRST so its cold compile doesn't pollute
        # the double-buffer row (the block program is shared either way).
        rows = {}
        for name, opts in (("sync", {"double_buffer": False}),
                           ("double_buffer", {})):
            eng = capacity_engine(**opts)
            dt = _timed_decode(eng, ids, new)
            rows[name] = {"tokens_per_sec": round(b * new / dt, 1),
                          "stall_ms": round(
                              eng._capacity.last_prefetch_stall_ms, 1)}
            eng.params = None
            del eng
        rows["speedup"] = round(rows["double_buffer"]["tokens_per_sec"]
                                / max(rows["sync"]["tokens_per_sec"], 1e-9),
                                2)
        print(json.dumps({"capacity_ab": rows}), flush=True)

    elif phase == "nvme":
        swap = os.environ.get("DS_TPU_SWAP_DIR", "/tmp/ds_capacity_swap")
        eng = capacity_engine(nvme_dir=swap,
                              nvme_layers=cfg.num_hidden_layers // 2)
        dt = _timed_decode(eng, ids, new)
        print(json.dumps({"capacity_nvme_decode": {
            "tokens_per_sec": round(b * new / dt, 1),
            "nvme_layers": eng._capacity.plan.nvme_layers,
            "nvme_gb": round(eng._capacity.plan.nvme_bytes / 1e9, 2),
            "stall_ms": round(eng._capacity.last_prefetch_stall_ms, 1)}}),
            flush=True)

    elif phase == "probe":
        # the r5 measurement, unchanged: pinned_host placement + plain jit
        # generate — documents WHY the engine stages explicitly
        from jax.sharding import NamedSharding, PartitionSpec as P
        groups.reset_topology()
        topo = groups.initialize(tp=1, dp=1, devices=jax.devices()[:1])
        host = NamedSharding(topo.mesh, P(), memory_kind="pinned_host")
        try:
            hparams = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, host), params)
            groups.reset_topology()
            eng = deepspeed_tpu.init_inference(model, params=hparams,
                                               dtype="bf16",
                                               auto_layouts=False)
            eng.params = hparams  # restore the residency under test
            out = eng.generate(ids, max_new_tokens=new)
            print(json.dumps({"probe": {"unexpectedly_ok": True,
                                        "distinct": int(len(np.unique(
                                            np.asarray(out))))}}),
                  flush=True)
        except Exception as e:
            print(json.dumps({"probe": {"outcome_b_error":
                                        str(e)[:220].replace("\n", " ")}}),
                  flush=True)


if __name__ == "__main__":
    main()
