"""ZeRO-Inference capacity mode probe: serve with params parked in HOST
memory (reference `deepspeed/inference/` ZeRO-Inference: weights live on
CPU/NVMe and stream to the accelerator per layer, trading bandwidth for
capacity — the path that serves models LARGER than device memory).

TPU mapping candidate: place the param tree with memory_kind='pinned_host'
NamedShardings and jit the usual generate — under the memories API XLA
must materialize device copies for compute; the question this probe
answers is WHERE it materializes them:

  (a) per-scan-slice (streams one layer's weights per step — capacity
      mode works, HBM peak ≈ one layer), or
  (b) whole-stack up-front (host placement buys nothing; a capacity mode
      needs an explicit per-layer device_put inside the scan body).

Run on the real chip: python benchmarks/capacity_serve.py [small|7b]

MEASURED (r5, 1×v5e): outcome (b). With params truly pinned_host the
first gather fails to compile — "memory_space of all inputs passed to
`gather` must be the same" — i.e. XLA does not auto-stage host operands
into compute, so a TPU ZeRO-Inference capacity mode needs an explicit
per-layer `jax.device_put` inside the layer scan (engine-level layer
loop over host-resident stacks, the chunk_fn machinery — r6 work). The
engine's own placement path (params re-placed to HBM) serves normally.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    from deepspeed_tpu.utils import groups

    big = "7b" in sys.argv[1:]
    if big:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=11008, num_hidden_layers=32,
                          num_attention_heads=32, num_key_value_heads=32,
                          max_position_embeddings=4096, remat=False,
                          dtype=jnp.bfloat16)
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=4096, num_hidden_layers=24,
                          num_attention_heads=8, num_key_value_heads=8,
                          max_position_embeddings=2048, remat=False,
                          dtype=jnp.bfloat16)
    groups.reset_topology()
    topo = groups.initialize(tp=1, dp=1, devices=jax.devices()[:1])
    model = LlamaForCausalLM(cfg)

    host = NamedSharding(topo.mesh, P(), memory_kind="pinned_host")

    def init_host():
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))
        raw, _ = extract_params_and_specs(variables)
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), raw)

    params = jax.jit(init_host,
                     out_shardings=host)()
    jax.block_until_ready(params)
    print(json.dumps({"params_gb": round(sum(
        v.nbytes for v in jax.tree_util.tree_leaves(params)) / 1e9, 2),
        "memory_kind": params and jax.tree_util.tree_leaves(
            params)[0].sharding.memory_kind}), flush=True)

    b, s, new = 4, 64, 16
    eng = deepspeed_tpu.init_inference(model, params=params, dtype="bf16",
                                       auto_layouts=False)
    # the engine re-places params into device memory; restore the HOST
    # residency under test (capacity mode bypasses engine placement)
    eng.params = params
    print(json.dumps({"engine_param_memory":
                      jax.tree_util.tree_leaves(eng.params)[0]
                      .sharding.memory_kind}), flush=True)
    ids = np.random.default_rng(1).integers(0, 32000, (b, s))
    try:
        t0 = time.time()
        out = eng.generate(ids, max_new_tokens=new)
        compile_s = round(time.time() - t0, 1)
        t0 = time.time()
        out = eng.generate(ids, max_new_tokens=new)
        dt = time.time() - t0
        print(json.dumps({"host_param_decode": {
            "tokens_per_sec": round(b * new / dt, 1),
            "compile_s": compile_s,
            "distinct": int(len(np.unique(np.asarray(out))))}}), flush=True)
    except Exception as e:
        print(json.dumps({"host_param_decode": {
            "error": str(e)[:220].replace("\n", " ")}}), flush=True)


if __name__ == "__main__":
    main()
