"""MoE on-chip breakdown (VERDICT r3 weak #3 / item 3).

Answers "is the one-hot/ragged dispatch the bottleneck, and is a
megablocks-style grouped-GEMM Pallas kernel needed?" with chained-loop
measurements at a mixtral-small-proxy shape on the real chip:

  1. experts-only batched GEMM at (E, C, D)        — the MXU floor
  2. ragged dispatch+combine with identity experts — scatter/gather cost
  3. einsum dispatch+combine with identity experts — one-hot matmul cost
  4. full MoE layer fwd (gate + dispatch + experts + combine), both impls
  5. full qwen2_moe-proxy TRAIN step MFU (the bench.py MoE row's source)

Usage: python benchmarks/moe_breakdown.py [pieces] [train]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_here = os.path.dirname(os.path.abspath(
    globals().get("__file__", "benchmarks/x")))
sys.path.insert(0, os.path.dirname(_here))


def main():
    import jax
    import jax.numpy as jnp

    phases = set(sys.argv[1:]) or {"pieces", "train"}
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    peak = 197e12

    # mixtral-small proxy: T tokens through E experts, top-2
    T, E, K, D, F = (8192, 8, 2, 1024, 2048) if on_tpu else (64, 4, 2, 32, 64)
    CF = 1.25
    key = jax.random.PRNGKey(0)

    if "pieces" in phases:
        from deepspeed_tpu.moe.sharded_moe import (
            _capacity, dispatch_combine, dispatch_combine_ragged, topkgating,
            topkgating_ragged)
        cap = _capacity(T, E, CF, 8, K)
        x = jax.random.normal(key, (T, D), jnp.bfloat16)
        wg = jax.random.normal(key, (D, E), jnp.float32) * 0.02
        w_up = jax.random.normal(key, (E, D, F), jnp.bfloat16) * 0.02
        w_gate = jax.random.normal(key, (E, D, F), jnp.bfloat16) * 0.02
        w_down = jax.random.normal(key, (E, F, D), jnp.bfloat16) * 0.02
        n_iter = 64 if on_tpu else 2
        res = {"tokens": T, "experts": E, "k": K, "capacity": cap}

        def experts_fn(ei):  # (E, C, D) -> (E, C, D), mixtral-style gated FFN
            import flax.linen as nn
            h = nn.silu(jnp.einsum("ecd,edf->ecf", ei, w_gate)) * \
                jnp.einsum("ecd,edf->ecf", ei, w_up)
            return jnp.einsum("ecf,efd->ecd", h, w_down)

        def chain(fn, x0):
            @jax.jit
            def run(xc):
                def body(i, xc):
                    return fn(xc).astype(xc.dtype)
                return jax.lax.fori_loop(0, n_iter, body, xc)
            float(run(x0).astype(jnp.float32).sum())
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                float(run(x0).astype(jnp.float32).sum())
                best = min(best, (time.perf_counter() - t0) / n_iter)
            return best

        ei = jax.random.normal(key, (E, cap, D), jnp.bfloat16)
        dt = chain(lambda v: experts_fn(v) * 1e-2, ei)
        gemm_flops = 6 * E * cap * D * F
        res["experts_gemm_ms"] = round(1e3 * dt, 2)
        res["experts_gemm_mfu"] = round(gemm_flops / dt / peak, 3)

        def ragged_path(xc, ident):
            logits = xc.astype(jnp.float32) @ wg
            l_aux, gate_k, topk_idx, pos_k, kept, cap_ = topkgating_ragged(
                logits, K, CF, 8)
            fn = (lambda v: v) if ident else experts_fn
            return dispatch_combine_ragged(xc, gate_k, topk_idx, pos_k, kept,
                                           cap_, E, fn) * 1e-2 + xc * 0.99

        def einsum_path(xc, ident):
            logits = xc.astype(jnp.float32) @ wg
            l_aux, combine, dispatch, _ = topkgating(logits, K, CF, 8)
            fn = (lambda v: v) if ident else experts_fn
            return dispatch_combine(xc, combine, dispatch, fn) * 1e-2 + xc * 0.99

        res["ragged_identity_ms"] = round(1e3 * chain(
            lambda v: ragged_path(v, True), x), 2)
        res["einsum_identity_ms"] = round(1e3 * chain(
            lambda v: einsum_path(v, True), x), 2)
        res["ragged_full_ms"] = round(1e3 * chain(
            lambda v: ragged_path(v, False), x), 2)
        res["einsum_full_ms"] = round(1e3 * chain(
            lambda v: einsum_path(v, False), x), 2)

        # grouped-GEMM (megablox) path: sort + 3 grouped matmuls + combine.
        # Its floor is the same 3 matmuls at fixed even groups — the
        # dispatch-overhead ratio gmm_full/gmm_gemm is what the CUTLASS
        # moe_gemm kernel exists to minimize.
        from deepspeed_tpu.moe.sharded_moe import (dispatch_combine_gmm,
                                                   topkgating_ragged)
        from deepspeed_tpu.ops.pallas.grouped_gemm import grouped_gemm

        def grouped_fn(rows, gs):
            import flax.linen as nn
            h = nn.silu(grouped_gemm(rows, w_gate, gs)) * \
                grouped_gemm(rows, w_up, gs)
            return grouped_gemm(h, w_down, gs)

        def gmm_path(xc):
            logits = xc.astype(jnp.float32) @ wg
            _, gate_k, topk_idx, _, _, _ = topkgating_ragged(logits, K, CF, 8)
            return dispatch_combine_gmm(xc, gate_k, topk_idx, E,
                                        grouped_fn) * 1e-2 + xc * 0.99

        res["gmm_full_ms"] = round(1e3 * chain(gmm_path, x), 2)
        rows = jax.random.normal(key, (T * K, D), jnp.bfloat16)
        gs_even = jnp.full((E,), T * K // E, jnp.int32)
        dt = chain(lambda v: grouped_fn(v, gs_even) * 1e-2 + v * 0.99, rows)
        res["gmm_gemm_ms"] = round(1e3 * dt, 2)
        res["gmm_gemm_mfu"] = round(6 * T * K * D * F / dt / peak, 3)
        res["gmm_dispatch_overhead"] = round(
            res["gmm_full_ms"] / max(res["gmm_gemm_ms"], 1e-9), 3)
        print(json.dumps({"pieces": res}))

    if "grad" in phases:
        # fwd+bwd of the FULL MoE layer per dispatch impl, chained in one
        # process — isolates where the train-step gap lives (the bwd).
        from deepspeed_tpu.moe.layer import MoE
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (1, T, D), jnp.bfloat16)
        out = {}
        for impl in ("ragged", "gmm", "einsum"):
            moe = MoE(hidden_size=D, num_experts=E, k=K,
                      intermediate_size=F, capacity_factor=CF,
                      dtype=jnp.bfloat16, dispatch_impl=impl)
            params = moe.init({"params": jax.random.PRNGKey(0)}, x)["params"]
            n_iter = 16 if on_tpu else 2

            def step(p, v):
                def loss(p):
                    o, _ = moe.apply({"params": p}, v, mutable=["aux_loss"])
                    return (o.astype(jnp.float32) ** 2).mean()
                return jax.grad(loss)(p)

            @jax.jit
            def run(p, v):
                def body(i, p):
                    g = step(p, v)
                    return jax.tree_util.tree_map(
                        lambda a, b: (a - 1e-6 * b.astype(a.dtype)), p, g)
                return jax.lax.fori_loop(0, n_iter, body, p)
            r = run(params, x)
            jax.block_until_ready(r)
            float(jax.tree_util.tree_leaves(r)[0].astype(jnp.float32).sum())
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                r = run(params, x)
                float(jax.tree_util.tree_leaves(r)[0]
                      .astype(jnp.float32).sum())
                best = min(best, (time.perf_counter() - t0) / n_iter)
            out[impl] = {"ms": round(1e3 * best, 3)}
        print(json.dumps({"grad": out}))

    if "gmmtune" in phases:
        # time the FULL grouped FFN (3 grouped GEMMs, same-shape feedback —
        # the experts_gemm harness form) per candidate tiling
        import flax.linen as nn
        from deepspeed_tpu.ops.pallas.grouped_gemm import grouped_gemm
        key = jax.random.PRNGKey(0)
        rows = jax.random.normal(key, (T * K, D), jnp.bfloat16)
        w_up = jax.random.normal(key, (E, D, F), jnp.bfloat16) * 0.02
        w_gate = jax.random.normal(key, (E, D, F), jnp.bfloat16) * 0.02
        w_down = jax.random.normal(key, (E, F, D), jnp.bfloat16) * 0.02
        gs_even = jnp.full((E,), T * K // E, jnp.int32)
        n_iter = 32 if on_tpu else 2
        out = {}
        for tiling in ((512, 512, 512), (512, 1024, 1024),
                       (1024, 512, 512), (1024, 1024, 1024),
                       (512, 1024, 2048), (1024, 1024, 2048),
                       (2048, 1024, 2048)):
            def ffn(v, tiling=tiling):
                h = nn.silu(grouped_gemm(v, w_gate, gs_even, tiling=tiling)) \
                    * grouped_gemm(v, w_up, gs_even, tiling=tiling)
                o = grouped_gemm(h, w_down, gs_even, tiling=tiling)
                return (o * 1e-2 + v * 0.99).astype(v.dtype)

            @jax.jit
            def run(v, ffn=ffn):
                return jax.lax.fori_loop(0, n_iter,
                                         lambda i, v: ffn(v), v)
            try:
                float(run(rows).astype(jnp.float32).sum())
                best = 1e9
                for _ in range(3):
                    t0 = time.perf_counter()
                    float(run(rows).astype(jnp.float32).sum())
                    best = min(best, (time.perf_counter() - t0) / n_iter)
                out[str(tiling)] = {
                    "ms": round(1e3 * best, 3),
                    "mfu": round(6 * T * K * D * F / best / peak, 3)}
            except Exception as e:
                out[str(tiling)] = {"error": str(e)[:120]}
        print(json.dumps({"gmmtune": out}))

    if "train" in phases:
        print(json.dumps({"train": moe_train_proxy(on_tpu)}))

    if "ab" in phases:
        # dispatch impl A/B in ONE process (cross-process timings swing ±25%)
        for impl, policy in (("ragged", "checkpoint_dots"),
                             ("gmm", "checkpoint_dots"),
                             ("gmm", "checkpoint_dots_gmm")):
            row = moe_train_proxy(on_tpu, dispatch_impl=impl,
                                  remat_policy=policy)
            print(json.dumps({f"train_{impl}_{policy}": row}))


def moe_train_proxy(on_tpu: bool, peak_tflops: float = 197.0,
                    dispatch_impl: str = "auto",
                    remat_policy: str = "checkpoint_dots",
                    mbs: int = 4, gas: int = 16,
                    remat: bool = True) -> dict:
    """Train the qwen2-moe one-chip proxy (BASELINE driver config 4's
    stand-in) and return the measured row. ONE source of truth — bench.py's
    MoE row and this harness's 'train' phase both call it."""
    import json
    import time

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.qwen2_moe import (
        Qwen2MoeConfig, init_qwen2_moe, qwen2_moe_loss_fn)
    from deepspeed_tpu.utils import groups

    if on_tpu:
        # ~550M params (250M active): one-chip proxy for BASELINE driver
        # config 4 (Mixtral-8x7B ZeRO-2 EP); fp32 master+Adam for the full
        # expert set must fit HBM alongside bf16 params+grads
        cfg = Qwen2MoeConfig(
            vocab_size=32000, hidden_size=1024,
            num_hidden_layers=8, num_attention_heads=8,
            num_key_value_heads=8, num_experts=8, num_experts_per_tok=2,
            moe_intermediate_size=2048,
            shared_expert_intermediate_size=2048,
            max_position_embeddings=2048, remat=remat,
            remat_policy=remat_policy, dispatch_impl=dispatch_impl,
            dtype=jnp.bfloat16)
        # mbs4 is the HBM ceiling (mbs6/8 OOM, r5). GAS16 amortizes the
        # ~36 ms/batch fixed cost (FusedAdam update over the FULL 552M
        # params + overflow reduce): 40.6% at GAS2 -> 45.7% GAS8 -> 46.4%
        # GAS16 (r5 one-process sweep)
        seq, steps, warmup = 2048, 4 if gas >= 8 else 8, 2
    else:
        cfg = Qwen2MoeConfig(
            vocab_size=512, hidden_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, num_experts=4, num_experts_per_tok=2,
            moe_intermediate_size=64, shared_expert_intermediate_size=64,
            max_position_embeddings=128, remat=remat,
            remat_policy=remat_policy, dispatch_impl=dispatch_impl,
            dtype=jnp.float32)
        mbs, seq, steps, warmup, gas = min(mbs, 2), 64, 2, 1, min(gas, 2)

    import numpy as np
    groups.reset_topology()
    model, params, specs = init_qwen2_moe(cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": mbs,
                "gradient_accumulation_steps": gas, "steps_per_print": 0,
                "optimizer": {"type": "FusedAdam", "params": {"lr": 1e-4}},
                "bf16": {"enabled": bool(on_tpu)},
                "zero_optimization": {"stage": 2}},
        loss_fn=qwen2_moe_loss_fn(model), base_param_specs=specs)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, size=(gas * mbs, seq)).astype(np.int32)}
    for _ in range(warmup):
        engine.train_batch(batch=batch)
    jax.block_until_ready(engine.state)
    t0 = time.time()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    jax.block_until_ready((engine.state, loss))
    dt = time.time() - t0
    tps = gas * mbs * seq * steps / dt
    # ACTIVE FLOPs/token: dense non-expert params + shared expert +
    # k-of-E routed experts (+ attention)
    n_total = engine.total_params
    expert_p = 3 * cfg.hidden_size * cfg.moe_intermediate_size * \
        cfg.num_experts * cfg.num_hidden_layers
    active = n_total - expert_p + expert_p * cfg.num_experts_per_tok \
        / cfg.num_experts
    fpt = 6.0 * active + 6.0 * cfg.num_hidden_layers * cfg.hidden_size * seq
    mfu = tps * fpt / 1e12 / peak_tflops if on_tpu else 0.0
    row = {"model": "qwen2moe-8x2048-proxy", "zero_stage": 2,
           "tokens_per_sec": round(tps, 1),
           "active_params_m": round(active / 1e6, 1),
           "total_params_m": round(n_total / 1e6, 1),
           "mfu_active": round(mfu, 4),
           "loss": round(float(loss), 4)}
    # free device state before whatever runs next
    engine.state = None
    engine._jit_cache.clear()
    del engine
    return row


if __name__ == "__main__":
    main()
