"""MoE on-chip breakdown (VERDICT r3 weak #3 / item 3).

Answers "is the one-hot/ragged dispatch the bottleneck, and is a
megablocks-style grouped-GEMM Pallas kernel needed?" with chained-loop
measurements at a mixtral-small-proxy shape on the real chip:

  1. experts-only batched GEMM at (E, C, D)        — the MXU floor
  2. ragged dispatch+combine with identity experts — scatter/gather cost
  3. einsum dispatch+combine with identity experts — one-hot matmul cost
  4. full MoE layer fwd (gate + dispatch + experts + combine), both impls
  5. full qwen2_moe-proxy TRAIN step MFU (the bench.py MoE row's source)

Usage: python benchmarks/moe_breakdown.py [pieces] [train]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_here = os.path.dirname(os.path.abspath(
    globals().get("__file__", "benchmarks/x")))
sys.path.insert(0, os.path.dirname(_here))


def main():
    import jax
    import jax.numpy as jnp

    phases = set(sys.argv[1:]) or {"pieces", "train"}
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    peak = 197e12

    # mixtral-small proxy: T tokens through E experts, top-2
    T, E, K, D, F = (8192, 8, 2, 1024, 2048) if on_tpu else (64, 4, 2, 32, 64)
    CF = 1.25
    key = jax.random.PRNGKey(0)

    if "pieces" in phases:
        from deepspeed_tpu.moe.sharded_moe import (
            _capacity, dispatch_combine, dispatch_combine_ragged, topkgating,
            topkgating_ragged)
        cap = _capacity(T, E, CF, 8, K)
        x = jax.random.normal(key, (T, D), jnp.bfloat16)
        wg = jax.random.normal(key, (D, E), jnp.float32) * 0.02
        w_up = jax.random.normal(key, (E, D, F), jnp.bfloat16) * 0.02
        w_gate = jax.random.normal(key, (E, D, F), jnp.bfloat16) * 0.02
        w_down = jax.random.normal(key, (E, F, D), jnp.bfloat16) * 0.02
        n_iter = 64 if on_tpu else 2
        res = {"tokens": T, "experts": E, "k": K, "capacity": cap}

        def experts_fn(ei):  # (E, C, D) -> (E, C, D), mixtral-style gated FFN
            import flax.linen as nn
            h = nn.silu(jnp.einsum("ecd,edf->ecf", ei, w_gate)) * \
                jnp.einsum("ecd,edf->ecf", ei, w_up)
            return jnp.einsum("ecf,efd->ecd", h, w_down)

        def chain(fn, x0):
            @jax.jit
            def run(xc):
                def body(i, xc):
                    return fn(xc).astype(xc.dtype)
                return jax.lax.fori_loop(0, n_iter, body, xc)
            float(run(x0).astype(jnp.float32).sum())
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                float(run(x0).astype(jnp.float32).sum())
                best = min(best, (time.perf_counter() - t0) / n_iter)
            return best

        ei = jax.random.normal(key, (E, cap, D), jnp.bfloat16)
        dt = chain(lambda v: experts_fn(v) * 1e-2, ei)
        gemm_flops = 6 * E * cap * D * F
        res["experts_gemm_ms"] = round(1e3 * dt, 2)
        res["experts_gemm_mfu"] = round(gemm_flops / dt / peak, 3)

        def ragged_path(xc, ident):
            logits = xc.astype(jnp.float32) @ wg
            l_aux, gate_k, topk_idx, pos_k, kept, cap_ = topkgating_ragged(
                logits, K, CF, 8)
            fn = (lambda v: v) if ident else experts_fn
            return dispatch_combine_ragged(xc, gate_k, topk_idx, pos_k, kept,
                                           cap_, E, fn) * 1e-2 + xc * 0.99

        def einsum_path(xc, ident):
            logits = xc.astype(jnp.float32) @ wg
            l_aux, combine, dispatch, _ = topkgating(logits, K, CF, 8)
            fn = (lambda v: v) if ident else experts_fn
            return dispatch_combine(xc, combine, dispatch, fn) * 1e-2 + xc * 0.99

        res["ragged_identity_ms"] = round(1e3 * chain(
            lambda v: ragged_path(v, True), x), 2)
        res["einsum_identity_ms"] = round(1e3 * chain(
            lambda v: einsum_path(v, True), x), 2)
        res["ragged_full_ms"] = round(1e3 * chain(
            lambda v: ragged_path(v, False), x), 2)
        res["einsum_full_ms"] = round(1e3 * chain(
            lambda v: einsum_path(v, False), x), 2)
        print(json.dumps({"pieces": res}))

    if "train" in phases:
        print(json.dumps({"train": moe_train_proxy(on_tpu)}))


def moe_train_proxy(on_tpu: bool, peak_tflops: float = 197.0) -> dict:
    """Train the qwen2-moe one-chip proxy (BASELINE driver config 4's
    stand-in) and return the measured row. ONE source of truth — bench.py's
    MoE row and this harness's 'train' phase both call it."""
    import json
    import time

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models.qwen2_moe import (
        Qwen2MoeConfig, init_qwen2_moe, qwen2_moe_loss_fn)
    from deepspeed_tpu.utils import groups

    if on_tpu:
        # ~550M params (250M active): one-chip proxy for BASELINE driver
        # config 4 (Mixtral-8x7B ZeRO-2 EP); fp32 master+Adam for the full
        # expert set must fit HBM alongside bf16 params+grads
        cfg = Qwen2MoeConfig(
            vocab_size=32000, hidden_size=1024,
            num_hidden_layers=8, num_attention_heads=8,
            num_key_value_heads=8, num_experts=8, num_experts_per_tok=2,
            moe_intermediate_size=2048,
            shared_expert_intermediate_size=2048,
            max_position_embeddings=2048, remat=True,
            remat_policy="checkpoint_dots", dtype=jnp.bfloat16)
        # mbs4/GAS2 beats mbs2/GAS4 (40.7% vs 39.2% active-MFU, r4):
        # the scatter/gather dispatch amortizes over 2x tokens/micro
        mbs, seq, steps, warmup, gas = 4, 2048, 8, 2, 2
    else:
        cfg = Qwen2MoeConfig(
            vocab_size=512, hidden_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, num_experts=4, num_experts_per_tok=2,
            moe_intermediate_size=64, shared_expert_intermediate_size=64,
            max_position_embeddings=128, remat=False, dtype=jnp.float32)
        mbs, seq, steps, warmup, gas = 2, 64, 2, 1, 2

    import numpy as np
    groups.reset_topology()
    model, params, specs = init_qwen2_moe(cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": mbs,
                "gradient_accumulation_steps": gas, "steps_per_print": 0,
                "optimizer": {"type": "FusedAdam", "params": {"lr": 1e-4}},
                "bf16": {"enabled": bool(on_tpu)},
                "zero_optimization": {"stage": 2}},
        loss_fn=qwen2_moe_loss_fn(model), base_param_specs=specs)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, size=(gas * mbs, seq)).astype(np.int32)}
    for _ in range(warmup):
        engine.train_batch(batch=batch)
    jax.block_until_ready(engine.state)
    t0 = time.time()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    jax.block_until_ready((engine.state, loss))
    dt = time.time() - t0
    tps = gas * mbs * seq * steps / dt
    # ACTIVE FLOPs/token: dense non-expert params + shared expert +
    # k-of-E routed experts (+ attention)
    n_total = engine.total_params
    expert_p = 3 * cfg.hidden_size * cfg.moe_intermediate_size * \
        cfg.num_experts * cfg.num_hidden_layers
    active = n_total - expert_p + expert_p * cfg.num_experts_per_tok \
        / cfg.num_experts
    fpt = 6.0 * active + 6.0 * cfg.num_hidden_layers * cfg.hidden_size * seq
    mfu = tps * fpt / 1e12 / peak_tflops if on_tpu else 0.0
    row = {"model": "qwen2moe-8x2048-proxy", "zero_stage": 2,
           "tokens_per_sec": round(tps, 1),
           "active_params_m": round(active / 1e6, 1),
           "total_params_m": round(n_total / 1e6, 1),
           "mfu_active": round(mfu, 4),
           "loss": round(float(loss), 4)}
    # free device state before whatever runs next
    engine.state = None
    engine._jit_cache.clear()
    del engine
    return row


if __name__ == "__main__":
    main()
