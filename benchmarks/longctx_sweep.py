"""128k long-context step-time probe (one variant per process — the lazy
allocator holds freed HBM, so chained variants OOM; CLAUDE.md bench note).

Usage: python benchmarks/longctx_sweep.py MLP_CHUNK CE_CHUNK {cpu|dev}
       [REMAT_POLICY] [SEQ] [GAS]
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import (
        LlamaConfig, init_params_and_specs, llama_loss_fn, materialize_params)
    from deepspeed_tpu.utils import groups

    mlp_chunk = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    ce_chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    opt_arg = sys.argv[3] if len(sys.argv) > 3 else "cpu"
    if opt_arg not in ("cpu", "dev"):
        raise SystemExit(f"OFFLOAD_OPT must be 'cpu' or 'dev', got {opt_arg!r}")
    offload = opt_arg == "cpu"
    policy = sys.argv[4] if len(sys.argv) > 4 else "host_offload"
    seq_l = int(sys.argv[5]) if len(sys.argv) > 5 else 131072
    gas = int(sys.argv[6]) if len(sys.argv) > 6 else 1

    groups.reset_topology()
    lcfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                       intermediate_size=4096, num_hidden_layers=24,
                       num_attention_heads=8, num_key_value_heads=8,
                       max_position_embeddings=seq_l, remat=True,
                       remat_policy=policy, loss_chunk_size=ce_chunk,
                       mlp_chunk_size=mlp_chunk, dtype=jnp.bfloat16)
    lmodel, lparams = materialize_params(lcfg)
    _, lspecs = init_params_and_specs(lcfg)
    zero = {"stage": 3}
    if offload:
        zero["offload_optimizer"] = {"device": "cpu"}
    lengine, *_ = deepspeed_tpu.initialize(
        model=lmodel, model_parameters=lparams,
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": gas, "steps_per_print": 0,
                "optimizer": {"type": "FusedAdam", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True}, "zero_optimization": zero},
        loss_fn=llama_loss_fn(lmodel), base_param_specs=lspecs)
    rng = np.random.default_rng(0)
    lb = {"input_ids": rng.integers(0, 32000, size=(gas, seq_l)).astype(np.int32)}
    float(lengine.train_batch(batch=lb))
    best = 1e9
    for _ in range(3):
        t0 = time.time()
        lloss = lengine.train_batch(batch=lb)
        float(lloss)  # axon: block_until_ready does not reliably block
        best = min(best, time.time() - t0)
    from deepspeed_tpu.accelerator import get_accelerator
    peak = get_accelerator().peak_tflops("bfloat16") or 197.0
    ltok = gas * seq_l / best
    lfpt = 6.0 * lengine.total_params + \
        6.0 * lcfg.num_hidden_layers * lcfg.hidden_size * seq_l
    print(json.dumps({
        "variant": f"mlp{mlp_chunk} ce{ce_chunk} "
                   f"{'cpu-opt' if offload else 'dev-opt'} {policy} s{seq_l} "
                   f"gas{gas}",
        "step_s": round(best, 2), "tokens_per_sec": round(ltok, 1),
        "mfu": round(ltok * lfpt / 1e12 / peak, 4)}))


if __name__ == "__main__":
    main()
