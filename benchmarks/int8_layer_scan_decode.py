"""int8 serving with PER-LAYER in-scan dequantization — the ZeRO-Inference
at-rest-quantized path at 7B scale (VERDICT r4 #1 / r5's named r6 fix).

The v1 engine dequantizes the WHOLE tree before model.apply, so int8 7 GB
+ bf16 13.5 GB coexist → OOM at 7B on a 16 GB v5e (measured,
benchmarks/hf7b_decode.py). This harness proves the fix: an engine-LEVEL
layer loop (`lax.scan` whose xs are the stacked int8+scales leaves — the
same per-layer slicing the pipeline chunk fns ride) dequantizes ONE
layer's weights inside the scan body, so the bf16 form is a ~0.4 GB
transient and peak HBM ≈ int8 tree + cache + one layer. Decode also
becomes weight-READ-bound at the int8 footprint: ~6.8 GB/step vs
13.5 GB/step for bf16 — the capacity win doubles as a throughput win.

Phases (combine freely on the CLI):
  (default)  7B layer-scan decode, NAIVE per-layer dequant (the r5 path)
  fused      7B layer-scan decode with the fused dequant-GEMM Pallas
             kernel on every matmul (ops/pallas/quantized_matmul.py)
  ab         single-process whole-LAYER A/B: fused vs naive decode-step
             layer forward, chained n_iter≥16 per the r5 measurement
             rules (tunnel noise makes single-matmul timings worthless)
  cpu        small-shape exact-parity check vs the whole-tree engine

MEASURED (r5, 1×v5e): CPU parity EXACT vs the engine over dequantized
params. 7B: int8 tree 7.63 GB on device and the layer-scan decode RUNS —
the capacity claim holds (a 13B int8 would fit where bf16 cannot). That
run predated two review fixes (norm stacks were also quantized; embed/head
landed f32 not bf16); post-fix the tree is 7.10 GB by exact accounting
(L·(int8 + scales/256·4B + bf16 norms) + bf16 embed/head — this harness
prints `quantized_tree_gb` to confirm on device). NAIVE throughput
40.8 tok/s @ b4 vs 162 bf16: the per-layer dequant MATERIALIZES f32/bf16
intermediates (~2.6 GB of HBM traffic per layer per step ≈ 98 ms/step,
matching measurement) because XLA does not fuse the block-reshape dequant
into the matmul operand read. The r6 `fused` phase removes exactly that:
decode weight reads drop to the at-rest bytes (6.84 GB/step vs 13.21
bf16-dense — see telemetry weight_bytes_step), so fused int8 targets
~2x FASTER than bf16, not 4x slower. r6 numbers pend the next on-chip
run (this round's sandbox has no TPU attached); the engine-integrated
path is benchmarked end-to-end by `hf7b_decode.py --int8`.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_quantized_decode(cfg, b: int, prompt: int, new: int, max_len: int,
                           fused: bool = False):
    """Compiled greedy generate over a layer-quantized llama param tree.

    Expects params with `layers` leaves quantized ({'__q8__', 'scales'}
    dicts, stacked (L, ...) on axis 0) and embed/norm/lm_head unquantized.
    `fused` swaps the naive dequantize-then-matmul layer body for the
    shared fused-kernel block (inference/quantized_layer_scan.py) — the
    same body the engine's quantized_layer_scan serve mode scans.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from deepspeed_tpu.inference.kv_cache import decode_mask
    from deepspeed_tpu.inference.quantization import dequantize_param_tree
    from deepspeed_tpu.models.llama import LlamaBlock, RMSNorm
    from deepspeed_tpu.ops.attention import rope_cos_sin

    block = LlamaBlock(cfg)
    final_norm = RMSNorm(cfg.rms_norm_eps, cfg.dtype)
    hd = cfg.head_dim

    if fused:
        from deepspeed_tpu.inference.quantized_layer_scan import make_block_fn
        fused_block = make_block_fn(cfg, fused=True)

        def layer_step(h, aux, layer_q, kv):
            return fused_block(h, layer_q, aux, kv)
    else:
        def layer_step(h, aux, layer_q, kv):
            lp = dequantize_param_tree(layer_q, dtype=cfg.dtype)
            out, new_kv = block.apply({"params": lp}, h, aux, kv=kv)
            return out, new_kv

    def forward(params, ids, cache_k, cache_v, index):
        embed = params["embed_tokens"].astype(cfg.dtype)
        h = jnp.take(embed, ids, axis=0)
        bsz, s = ids.shape
        positions = index[:, None] + jnp.arange(s)[None, :]
        cos, sin = rope_cos_sin(positions, hd, cfg.rope_theta, cfg.dtype)
        mask = decode_mask(positions, max_len)
        aux = (cos, sin, index, mask)

        def body(h, xs):
            layer_q, k_l, v_l = xs
            h, (k_new, v_new) = layer_step(h, aux, layer_q, (k_l, v_l))
            return h, (k_new, v_new)

        h, (k_new, v_new) = lax.scan(
            body, h, (params["layers"], cache_k, cache_v))
        h = final_norm.apply({"params": params["norm"]}, h)
        head = params.get("lm_head")
        if head is None:
            logits = h @ embed.T
        else:
            logits = h @ head.astype(cfg.dtype)
        return logits, k_new, v_new

    def gen(params, ids):
        bsz = ids.shape[0]
        L = cfg.num_hidden_layers
        cache_k = jnp.zeros((L, bsz, max_len, cfg.num_key_value_heads, hd),
                            cfg.dtype)
        cache_v = jnp.zeros_like(cache_k)
        index0 = jnp.zeros((bsz,), jnp.int32)
        logits, cache_k, cache_v = forward(params, ids, cache_k, cache_v,
                                           index0)
        tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

        def step(carry, _):
            cache_k, cache_v, tok, index = carry
            logits, cache_k, cache_v = forward(
                params, tok[:, None], cache_k, cache_v, index)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (cache_k, cache_v, nxt, index + 1), tok

        carry = (cache_k, cache_v, tok, jnp.full((bsz,), prompt, jnp.int32))
        (cache_k, cache_v, last, _), toks = lax.scan(
            step, carry, None, length=new - 1)
        return jnp.concatenate([toks.T, last[:, None]], axis=1)

    return gen


def ab_phase(on_cpu: bool, n_iter: int = 32, repeats: int = 3):
    """Single-process whole-LAYER A/B: one decode-step layer forward
    (7 matmuls + rope + cached attention + norms) chained `n_iter` times
    inside ONE jit, fused dequant-GEMM vs naive dequantize-then-matmul
    over the SAME quantized leaves. Per the r5 rules: whole layers, one
    process, best-of-`repeats`, real fetch at the end of each chain."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from deepspeed_tpu.inference.kv_cache import decode_mask
    from deepspeed_tpu.inference.quantized_layer_scan import make_block_fn
    from deepspeed_tpu.models.llama import LlamaConfig
    from deepspeed_tpu.ops.attention import rope_cos_sin
    from deepspeed_tpu.ops.quantization import quantize_int8_blockwise

    if on_cpu:  # functional smoke only — interpret-mode Pallas is slow
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=256, num_hidden_layers=1,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=128, remat=False,
                          attn_impl="xla", dtype=jnp.float32)
        b, n_iter, repeats = 2, 2, 1
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=11008, num_hidden_layers=1,
                          num_attention_heads=32, num_key_value_heads=32,
                          max_position_embeddings=4096, remat=False,
                          dtype=jnp.bfloat16)
        b = 4
    d, f, hd = cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
    max_len = 128
    tile = (np.arange(1 << 14) % 251).astype(np.float32) * 1e-3

    def mk(shape):
        n = int(np.prod(shape))
        reps = -(-n // tile.size)
        return jnp.asarray(np.tile(tile, reps)[:n].reshape(shape), cfg.dtype)

    def qz(x):
        qv, s = quantize_int8_blockwise(x)
        return {"kernel": {"__q8__": qv, "scales": s}}

    kvd = cfg.num_key_value_heads * hd
    lp = {"self_attn": {"q_proj": qz(mk((d, d))),
                        "k_proj": qz(mk((d, kvd))),
                        "v_proj": qz(mk((d, kvd))),
                        "o_proj": qz(mk((d, d)))},
          "mlp": {"gate_proj": qz(mk((d, f))), "up_proj": qz(mk((d, f))),
                  "down_proj": qz(mk((f, d)))},
          "input_layernorm": {"weight": jnp.ones((d,), jnp.float32)},
          "post_attention_layernorm": {"weight": jnp.ones((d,), jnp.float32)}}
    jax.block_until_ready(lp)

    h0 = mk((b, 1, d))
    kv0 = (jnp.zeros((b, max_len, cfg.num_key_value_heads, hd), cfg.dtype),
           jnp.zeros((b, max_len, cfg.num_key_value_heads, hd), cfg.dtype))
    index = jnp.full((b,), 64, jnp.int32)  # mid-cache decode position
    positions = index[:, None]
    cos, sin = rope_cos_sin(positions, hd, cfg.rope_theta, cfg.dtype)
    mask = decode_mask(positions, max_len)
    aux = (cos, sin, index, mask)

    def chain(block):
        def run(lp, h, kv):
            def body(_, carry):
                h, kv = carry
                h, kv = block(h, lp, aux, kv)
                return (h, kv)
            h, kv = lax.fori_loop(0, n_iter, body, (h, kv))
            return h.astype(jnp.float32).sum()  # tiny fetch forces the work
        return jax.jit(run)

    row = {}
    for name, fused in (("naive", False), ("fused", True)):
        fn = chain(make_block_fn(cfg, fused=fused))
        _ = float(fn(lp, h0, kv0))  # compile + warm
        best = 1e9
        for _ in range(repeats):
            t0 = time.time()
            _ = float(fn(lp, h0, kv0))
            best = min(best, time.time() - t0)
        row[name + "_ms_per_layer"] = round(best / n_iter * 1e3, 3)
    row["fused_speedup"] = round(
        row["naive_ms_per_layer"] / max(row["fused_ms_per_layer"], 1e-9), 2)
    row["n_iter"] = n_iter
    print(json.dumps({"layer_ab": row}), flush=True)


def main():
    import jax
    import jax.numpy as jnp
    import jax.tree_util as jtu
    from deepspeed_tpu.inference.quantization import quantize_param_tree
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.utils.partitioning import extract_params_and_specs
    from deepspeed_tpu.utils import groups

    on_cpu = "cpu" in sys.argv[1:]
    fused = "fused" in sys.argv[1:]
    if "ab" in sys.argv[1:]:
        ab_phase(on_cpu)
        return
    if on_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = flags + \
                " --xla_force_host_platform_device_count=1"
        jax.config.update("jax_platforms", "cpu")
        cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                          intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=128, remat=False,
                          attn_impl="xla", dtype=jnp.float32)
        b, prompt, new = 2, 8, 6
    else:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=4096,
                          intermediate_size=11008, num_hidden_layers=32,
                          num_attention_heads=32, num_key_value_heads=32,
                          max_position_embeddings=4096, remat=False,
                          dtype=jnp.bfloat16)
        b, prompt, new = 4, 64, 32
    max_len = 128

    groups.reset_topology()
    model = LlamaForCausalLM(cfg)

    def init_params():
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))
        raw, _ = extract_params_and_specs(variables)
        return jtu.tree_map(lambda x: x.astype(cfg.dtype), raw)

    if on_cpu:
        params = jax.jit(init_params)()
        jax.block_until_ready(params)
    else:
        # build by SHAPE on the host: a 13.5 GB bf16 device tree would
        # leave the lazy allocator unable to serve the generate phase
        # even after frees (CLAUDE.md bench gotcha), and a real host-side
        # random init costs 10+ min on this 1-core box. Values are a
        # cheap tiled ramp — the measurement is weight-READ-bound perf
        # (numeric parity is proven exactly on the CPU path above).
        shapes = jax.eval_shape(init_params)
        tile = (np.arange(1 << 16) % 251).astype(np.float32) * 1e-3

        def mk(sd):
            n = int(np.prod(sd.shape))
            reps = -(-n // tile.size)
            return np.tile(tile, reps)[:n].reshape(sd.shape).astype(sd.dtype)
        params = jtu.tree_map(mk, shapes)

    # quantize ONLY the layer stacks, PER LAYER (vmap over the stacked
    # axis) so scales carry a leading L dim and lax.scan can slice them;
    # embed/norm/head stay unquantized
    from deepspeed_tpu.ops.quantization import quantize_int8_blockwise

    q_one = jax.jit(lambda t: quantize_int8_blockwise(t))

    def q_stacked(x):
        # kernels are 3-D stacked (L, in, out); 2-D stacks are the
        # per-layer NORM weights, which stay full precision (the engine's
        # quantize_param_tree skips norms/biases too)
        if x.ndim >= 3 and x[0].size >= 4096:
            if on_cpu:
                qv, s = jax.jit(jax.vmap(
                    lambda t: quantize_int8_blockwise(t)))(x)
                return {"__q8__": qv, "scales": s}
            # 7B path: one layer at a time — the whole-stack vmap's f32
            # temps are 2x the leaf (5.4 GB for the mlp stacks) and OOM
            # the chip during the quantization phase itself
            qs, ss = [], []
            for l in range(x.shape[0]):
                q_l, s_l = q_one(jnp.asarray(x[l]))
                jax.block_until_ready((q_l, s_l))
                qs.append(q_l)
                ss.append(s_l)
            return {"__q8__": jnp.stack(qs), "scales": jnp.stack(ss)}
        return x

    # leaf-wise REPLACEMENT: rebinding each leaf frees its bf16 form
    # before the next quantizes, so peak HBM ≈ bf16 tree + one leaf
    leaves, treedef = jtu.tree_flatten(params["layers"])
    rest = {k: v for k, v in params.items() if k != "layers"}
    del params
    for i in range(len(leaves)):
        q = q_stacked(leaves[i])
        jax.block_until_ready(q)
        leaves[i] = q
    qparams = dict(rest, layers=jtu.tree_unflatten(treedef, leaves))
    del leaves
    q_bytes = sum(getattr(l, "nbytes", 0)
                  for l in jtu.tree_leaves(qparams))
    print(json.dumps({"quantized_tree_gb": round(q_bytes / 1e9, 2)}),
          flush=True)

    gen = build_quantized_decode(cfg, b, prompt, new, max_len, fused=fused)
    ids = jnp.asarray(np.random.default_rng(1).integers(
        0, cfg.vocab_size, (b, prompt)), jnp.int32)
    t0 = time.time()
    if on_cpu:
        jfn = jax.jit(gen)
    else:
        # AUTO input layouts + leaf-wise re-placement (the
        # InferenceEngine._compile_auto_layout recipe, duplicated here
        # because this harness bypasses the engine; see that method's
        # NOTE for the sole-reference caveat): without it XLA copies the
        # int8 stacks to its preferred tiling in-program and OOMs
        from deepspeed_tpu.utils.layouts import (
            auto_input_format, compiled_input_formats)
        jitted = jax.jit(gen, in_shardings=auto_input_format())
        abstract = jtu.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), qparams)
        compiled = jitted.lower(
            abstract, jax.ShapeDtypeStruct(ids.shape, ids.dtype)).compile()
        fmts = compiled_input_formats(compiled)[0]
        qleaves, qdef = jtu.tree_flatten(qparams)
        fmt_leaves = jtu.tree_leaves(fmts[0])
        del qparams
        for i, fmt in enumerate(fmt_leaves):
            new_leaf = jax.device_put(qleaves[i], fmt)
            new_leaf.block_until_ready()
            qleaves[i] = new_leaf
        qparams = jtu.tree_unflatten(qdef, qleaves)
        del qleaves
        ids = jax.device_put(ids, fmts[1])
        jfn = compiled
    out = np.asarray(jfn(qparams, ids))
    compile_s = round(time.time() - t0, 1)
    best = 1e9
    for _ in range(3):
        t0 = time.time()
        out = np.asarray(jfn(qparams, ids))
        best = min(best, time.time() - t0)
    print(json.dumps({"int8_layer_scan_decode": {
        "impl": "fused" if fused else "naive",
        "batch": b, "new_tokens": new,
        "full_gen_s": round(best, 3),
        "decode_tokens_per_sec": round(b * new / best, 1),
        "compile_s": compile_s,
        "distinct": int(len(np.unique(out)))}}), flush=True)

    if on_cpu:
        # parity vs the zoo model with DEQUANTIZED params (same weights);
        # the stacked (L-leading) form dequantizes per layer via vmap
        from deepspeed_tpu.inference.quantization import is_quantized_leaf
        from deepspeed_tpu.ops.quantization import dequantize_int8_blockwise

        def dq_stacked(leaf):
            if is_quantized_leaf(leaf):
                return jax.vmap(lambda q, s: dequantize_int8_blockwise(
                    q, s, cfg.dtype))(leaf["__q8__"], leaf["scales"])
            return leaf

        dq = dict(qparams, layers=jtu.tree_map(
            dq_stacked, qparams["layers"], is_leaf=is_quantized_leaf))
        import deepspeed_tpu
        eng = deepspeed_tpu.init_inference(model, params=dq, dtype="fp32",
                                           auto_layouts=False)
        ref = eng.generate(np.asarray(ids), max_new_tokens=new)
        np.testing.assert_array_equal(out, np.asarray(ref)[:, prompt:])
        print(json.dumps({"cpu_parity": "exact"}), flush=True)


if __name__ == "__main__":
    main()
