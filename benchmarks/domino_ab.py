"""Domino A/B (VERDICT r4 weak #5): is the two-chunk batch interleave
(reference `runtime/domino/transformer.py`, blog claim 1.2-1.3x) worth
anything under XLA, which already runs a latency-hiding scheduler?

Method (one process; real multi-chip TP is unavailable on this box, so
the evidence is (a) wall-clock on the virtual-CPU TP mesh and (b) the
collective STRUCTURE of the compiled programs):

  1. llama train step at tp=2 (dp fills the rest), domino off vs on —
     chained steps, best-of-3.
  2. optimized-HLO accounting of both programs: all-reduce count and how
     many are ASYNC pairs (`all-reduce-start`/`-done`) with compute
     scheduled between — XLA's own overlap, no hand scheduling.

Usage: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python benchmarks/domino_ab.py [tpu]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    if "tpu" not in sys.argv[1:]:
        os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
            " --xla_force_host_platform_device_count=8"
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import (
        llama_config, llama_loss_fn, materialize_params)
    from deepspeed_tpu.utils import groups

    out = {}
    for domino in (False, True):
        groups.reset_topology()
        cfg = llama_config("llama-tiny", dtype=jnp.float32, domino=domino,
                           hidden_size=256, intermediate_size=512,
                           num_hidden_layers=4, num_attention_heads=8,
                           num_key_value_heads=8)
        model, params = materialize_params(cfg)
        topo = groups.MeshTopology(tp=2)
        engine, *_ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            loss_fn=llama_loss_fn(model), topology=topo,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": 1, "steps_per_print": 0,
                    "optimizer": {"type": "FusedAdam", "params": {"lr": 1e-4}},
                    "zero_optimization": {"stage": 0}})
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(
            0, cfg.vocab_size,
            (4 * topo.dense_dp_size, 64)).astype(np.int32)}
        losses = [float(engine.train_batch(batch=batch)) for _ in range(2)]
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(8):
                engine.train_batch(batch=batch)
            jax.block_until_ready(engine.state)
            best = min(best, (time.perf_counter() - t0) / 8)
        key = "domino" if domino else "plain"
        out[key] = {"step_ms": round(1e3 * best, 2),
                    "loss": round(losses[-1], 4)}

        # collective structure of the compiled fwd+bwd under the same
        # mesh/shardings (counts per ONE micro step)
        loss_fn = llama_loss_fn(model)
        rng_key = jax.random.PRNGKey(0)
        micro = {"input_ids": batch["input_ids"][:4]}

        def fwd_bwd(p, b, r):
            return jax.grad(lambda p: loss_fn(p, b, r)[0]
                            if isinstance(loss_fn(p, b, r), tuple)
                            else loss_fn(p, b, r))(p)
        with engine.mesh:
            txt = jax.jit(fwd_bwd).lower(
                engine.state.params, micro, rng_key).compile().as_text()
        out[key]["all_reduce_ops"] = txt.count(" all-reduce(")
        out[key]["async_all_reduce_starts"] = txt.count("all-reduce-start")
    if "plain" in out and "domino" in out:
        out["domino_speedup"] = round(
            out["plain"]["step_ms"] / out["domino"]["step_ms"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
