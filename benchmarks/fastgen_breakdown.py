"""FastGen serving-path breakdown (VERDICT r3 weak #1).

Splits the continuous-batching gap (499 decode tok/s vs 3594 plain decode)
into its parts on the real chip:

  gen      — instrumented generate(): per-compiled-program wall time + call
             counts (sync timing), host-side scheduling remainder.
  dispatch — warm dispatch latency of the decode-scan program: async submit
             time vs synced round-trip (axon tunnel RTT).
  kernels  — chained fori_loop micro-bench (CLAUDE.md method): paged decode
             kernel vs dense decode kernel vs XLA masked path vs the paged
             scatter (update_layer), at the serving shape.

Usage: python benchmarks/fastgen_breakdown.py [gen] [dispatch] [kernels]
                                              [--serve-mode=MODE]

--serve-mode routes the engine through a big-model serve mode
(dequant | layer_scan | capacity); the streamed modes quantize the tree
(quant enabled) and ride the dense 'slot' KV layout the engine forces.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.models.llama import LlamaConfig, materialize_params
    from deepspeed_tpu.utils import groups

    serve_mode = None
    argv = []
    for a in sys.argv[1:]:
        if a.startswith("--serve-mode="):
            serve_mode = a.split("=", 1)[1]
        else:
            argv.append(a)
    phases = set(argv) or {"gen", "dispatch", "kernels"}
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")

    # Program ledger: every v2 serving program this harness compiles gets a
    # cost/memory/roofline row (captured at first dispatch — compile time,
    # not the timed loops). Diff across runs with
    # `python -m deepspeed_tpu.telemetry --diff-ledger old new`.
    from deepspeed_tpu.telemetry import ledger as ledger_mod
    ledger_path = os.environ.get("DS_TPU_LEDGER_JSONL",
                                 "ledger_fastgen.jsonl")
    ledger = ledger_mod.set_ledger(
        ledger_mod.ProgramLedger(path=ledger_path, enabled=True))

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=4096, num_hidden_layers=24,
                          num_attention_heads=8, num_key_value_heads=8,
                          max_position_embeddings=2048, remat=False,
                          dtype=jnp.bfloat16)
        n_q, mb, msl, plen, new, blocks, chunk = 96, 64, 1024, 256, 64, 96, 256
    else:
        cfg = LlamaConfig(vocab_size=1024, hidden_size=128,
                          intermediate_size=256, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=256, remat=False,
                          dtype=jnp.float32)
        n_q, mb, msl, plen, new, blocks, chunk = 6, 4, 64, 12, 4, 6, 8

    rng = np.random.default_rng(0)
    groups.reset_topology()
    model, params = materialize_params(cfg)

    report = {}

    def make_engine():
        groups.reset_topology()
        kw = dict(max_batch=mb, max_seq_len=msl, split_fuse_chunk=chunk)
        if serve_mode in (None, "dequant"):
            kw.update(kv_layout="paged", num_cache_blocks=blocks)
        else:
            # streamed modes force the dense 'slot' layout and need a
            # quantized tree (layer_scan) / stream host slices (capacity)
            kw.update(quant={"enabled": True})
        if serve_mode is not None:
            kw.update(serve_mode=serve_mode)
        return InferenceEngineV2(model, params=params, **kw)

    prompts = [list(rng.integers(0, cfg.vocab_size, plen)) for _ in range(n_q)]

    if "gen" in phases:
        if os.environ.get("DS_BENCH_LOG_COMPILES"):
            jax.config.update("jax_log_compiles", True)
        stats = {}
        percall = {}

        class TimingDict(dict):
            def __setitem__(self, key, fn):
                @functools.wraps(fn)
                def wrapped(*a, **k):
                    t0 = time.perf_counter()
                    out = fn(*a, **k)
                    jax.block_until_ready(out)
                    dt = time.perf_counter() - t0
                    s = stats.setdefault(str(key), [0.0, 0])
                    s[0] += dt
                    s[1] += 1
                    percall.setdefault(str(key), []).append(round(dt, 3))
                    return out
                super().__setitem__(key, wrapped)

        v2 = make_engine()
        v2._jits = TimingDict()
        host = {}

        def wrap(obj, name):
            fn = getattr(obj, name)
            def wrapped(*a, **k):
                t0 = time.perf_counter()
                out = fn(*a, **k)
                host.setdefault(name, [0.0, 0])
                host[name][0] += time.perf_counter() - t0
                host[name][1] += 1
                return out
            setattr(obj, name, wrapped)
        for name in ("_flush_batch", "_maybe_sync_tables", "_reserve", "put"):
            wrap(v2, name)
        v2.generate(prompts[:4], max_new_tokens=new)  # compile
        stats.clear()
        host.clear()
        t0 = time.perf_counter()
        v2.generate(prompts, max_new_tokens=new)
        wall = time.perf_counter() - t0
        dispatch_total = sum(s[0] for s in stats.values())
        report["gen"] = {
            "wall_s": round(wall, 3),
            "decode_tok_s": round(n_q * new / wall, 1),
            "dispatch_s": round(dispatch_total, 3),
            "host_s": round(wall - dispatch_total, 3),
            "programs": {k: {"s": round(s[0], 3), "calls": s[1],
                             "ms_per_call": round(1e3 * s[0] / s[1], 1),
                             "per_call": percall[k]}
                         for k, s in sorted(stats.items())},
            "host_sections": {k: {"s": round(s[0], 3), "calls": s[1]}
                              for k, s in sorted(host.items())},
        }
        v2.cache = None
        del v2

    if "dispatch" in phases:
        v2 = make_engine()
        # warm the decode-scan program via a tiny generate
        v2.generate(prompts[:4], max_new_tokens=new)
        k = 16 if on_tpu else 4
        fn = v2._decode_scan_fn(k)
        tokens = jnp.zeros((mb, 1), jnp.int32)
        active = jnp.ones((mb,), bool)
        # park all cursors at 256 so steps write in-bounds
        v2.cache = v2.cache.replace(
            index=jnp.full((mb,), plen, jnp.int32))
        if v2.kv_layout == "paged":
            v2._tables_np[:] = np.arange(
                mb * v2._tables_np.shape[1]).reshape(mb, -1) % blocks
            v2._tables_dirty = True
            v2._maybe_sync_tables()
        rng = jax.random.PRNGKey(0)
        fold = jnp.asarray(v2._slot_uids, jnp.int32)
        cache, toks = fn(v2.params, v2.cache, tokens, active, rng, fold)
        jax.block_until_ready(toks)
        reps = 6
        # synced round-trips
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            cache, toks = fn(v2.params, cache, tokens, active, rng, fold)
            jax.block_until_ready(toks)
            ts.append(time.perf_counter() - t0)
        # async submit cost (dispatch only)
        t0 = time.perf_counter()
        for _ in range(reps):
            cache, toks = fn(v2.params, cache, tokens, active, rng, fold)
        submit = (time.perf_counter() - t0) / reps
        jax.block_until_ready(toks)
        report["dispatch"] = {
            "decode_scan_k": k,
            "sync_ms": round(1e3 * float(np.median(ts)), 1),
            "per_token_ms": round(1e3 * float(np.median(ts)) / k, 2),
            "async_submit_ms": round(1e3 * submit, 1),
        }
        # measured wall onto the scan program's ledger row (the engine's
        # _track owns the name — streamed modes carry an @serve_mode
        # suffix, int8 caches @kv_int8)
        ledger.observe_measured(f"v2:{fn._ds_program}",
                                1e3 * float(np.median(ts)))
        v2.cache = None
        del v2

    if "kernels" in phases:
        from deepspeed_tpu.ops.attention import reference_attention
        from deepspeed_tpu.ops.pallas.decode_attention import decode_attention
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_decode_attention)

        L = 1  # single layer shapes; model has 24 of these per step
        hkv = cfg.num_key_value_heads
        h = cfg.num_attention_heads
        d = cfg.head_dim
        bs = 256 if on_tpu else 16
        t = msl // bs
        length = plen + new  # 320: the serving steady state
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (mb, 1, h, d), cfg.dtype)
        k_pool = jax.random.normal(key, (hkv, blocks, bs, d), cfg.dtype)
        v_pool = jax.random.normal(key, (hkv, blocks, bs, d), cfg.dtype)
        # realistic tables: each row owns ceil(length/bs) blocks
        own = -(-length // bs)
        tables = np.full((mb, t), -1, np.int32)
        nxt = 0
        for b in range(mb):
            for j in range(own):
                tables[b, j] = nxt % blocks
                nxt += 1
        tables = jnp.asarray(tables)
        lengths = jnp.full((mb,), length, jnp.int32)

        k_dense = jax.random.normal(key, (mb, msl, hkv, d), cfg.dtype)
        v_dense = jax.random.normal(key, (mb, msl, hkv, d), cfg.dtype)
        mask = (jnp.arange(msl)[None, None, :] <
                lengths[:, None, None])

        # big enough that the ~120ms tunnel RTT per run() is noise per-iter
        n_iter = 512 if on_tpu else 2

        def chain(fn):
            @jax.jit
            def run(q0):
                def body(i, q):
                    o = fn(q)
                    return o.astype(q.dtype)
                return jax.lax.fori_loop(0, n_iter, body, q0)
            run(q).block_until_ready()  # compile
            t0 = time.perf_counter()
            run(q).block_until_ready()
            return 1e3 * (time.perf_counter() - t0) / n_iter

        res = {}
        res["paged_kernel_ms"] = round(chain(
            lambda q: paged_decode_attention(q, k_pool, v_pool, tables,
                                             lengths)), 3)
        res["dense_kernel_ms"] = round(chain(
            lambda q: decode_attention(q, k_dense, v_dense, lengths)), 3)
        res["xla_masked_ms"] = round(chain(
            lambda q: reference_attention(q, k_dense, v_dense, causal=False,
                                          segment_mask=mask)), 3)

        # the paged scatter (update_layer) at decode shape
        from deepspeed_tpu.inference.kv_cache import (PagedLayer,
                                                      _update_paged_layer)
        layer = PagedLayer(pool=k_pool, tables=tables)
        kn = jax.random.normal(key, (mb, 1, hkv, d), cfg.dtype)

        @jax.jit
        def scat(layer, kn):
            def body(i, lay):
                return _update_paged_layer(lay, kn, lengths)
            return jax.lax.fori_loop(0, n_iter, body, layer)
        scat(layer, kn).pool.block_until_ready()
        t0 = time.perf_counter()
        scat(layer, kn).pool.block_until_ready()
        res["paged_scatter_ms"] = round(
            1e3 * (time.perf_counter() - t0) / n_iter, 3)

        # dense scatter comparison
        @jax.jit
        def scat_d(kc, kn):
            def body(i, kc):
                rows = jnp.arange(mb)[:, None]
                cols = lengths[:, None] + jnp.arange(1)[None, :]
                return kc.at[rows, cols].set(kn, mode="drop")
            return jax.lax.fori_loop(0, n_iter, body, kc)
        scat_d(k_dense, kn).block_until_ready()
        t0 = time.perf_counter()
        scat_d(k_dense, kn).block_until_ready()
        res["dense_scatter_ms"] = round(
            1e3 * (time.perf_counter() - t0) / n_iter, 3)
        report["kernels"] = res

    if "prefill" in phases:
        # Isolate the chunk_batch program's pieces at serving shape.
        import jax
        from deepspeed_tpu.inference.kv_cache import (PagedLayer,
                                                      _update_paged_layer)
        from deepspeed_tpu.ops.pallas.paged_attention import (
            paged_prefill_attention)
        res = {}
        hkv = cfg.num_key_value_heads
        h = cfg.num_attention_heads
        d = cfg.head_dim
        bs = 256 if on_tpu else 16
        t = msl // bs
        key = jax.random.PRNGKey(0)
        C = chunk
        q = jax.random.normal(key, (mb, C, h, d), cfg.dtype)
        k_pool = jax.random.normal(key, (hkv, blocks, bs, d), cfg.dtype)
        v_pool = jax.random.normal(key, (hkv, blocks, bs, d), cfg.dtype)
        tables = jnp.asarray(
            (np.arange(mb * t).reshape(mb, t) % blocks).astype(np.int32))
        starts = jnp.zeros((mb,), jnp.int32)
        n_iter = 64 if on_tpu else 2

        @jax.jit
        def pf_chain(q0):
            def body(i, q):
                return paged_prefill_attention(q, k_pool, v_pool, tables,
                                               starts).astype(q.dtype)
            return jax.lax.fori_loop(0, n_iter, body, q0)
        pf_chain(q).block_until_ready()
        t0 = time.perf_counter()
        pf_chain(q).block_until_ready()
        res["prefill_kernel_ms"] = round(
            1e3 * (time.perf_counter() - t0) / n_iter, 3)

        kn = jax.random.normal(key, (mb, C, hkv, d), cfg.dtype)
        layer = PagedLayer(pool=k_pool, tables=tables)
        for name, idx in (("chunk_scatter_aligned_ms", starts),
                          ("chunk_scatter_misaligned_ms",
                           jnp.full((mb,), 3, jnp.int32))):
            @jax.jit
            def sc_chain(lay, kn, idx=idx):
                def body(i, lay):
                    return _update_paged_layer(lay, kn, idx)
                return jax.lax.fori_loop(0, n_iter, body, lay)
            sc_chain(layer, kn).pool.block_until_ready()
            t0 = time.perf_counter()
            sc_chain(layer, kn).pool.block_until_ready()
            res[name] = round(1e3 * (time.perf_counter() - t0) / n_iter, 3)

        # the whole chunk_batch program, sync-timed warm, vs a plain
        # full-model forward on the same token count (the compute floor)
        v2 = make_engine()
        v2._tables_np[:] = np.asarray(tables)
        v2._tables_dirty = True
        v2._maybe_sync_tables()
        fn = v2._chunk_batch_fn()
        ids = jnp.zeros((mb, C), jnp.int32)
        slots = jnp.arange(mb, dtype=jnp.int32)
        valids = jnp.full((mb,), C, jnp.int32)
        cache, last = fn(v2.params, v2.cache, ids, slots, starts, valids)
        jax.block_until_ready(last)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            cache, last = fn(v2.params, cache, ids, slots, starts, valids)
            jax.block_until_ready(last)
            ts.append(time.perf_counter() - t0)
        res["chunk_batch_sync_ms"] = round(1e3 * float(np.median(ts)), 1)

        model_fwd = jax.jit(lambda p, i: model.apply({"params": p}, i))
        logits = model_fwd(v2.params, ids)
        jax.block_until_ready(logits)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            logits = model_fwd(v2.params, ids)
            jax.block_until_ready(logits)
            ts.append(time.perf_counter() - t0)
        res["plain_fwd_same_tokens_ms"] = round(1e3 * float(np.median(ts)), 1)
        report["prefill"] = res

    report["serve_mode"] = serve_mode or "dequant"
    report["ledger"] = {"path": ledger_path,
                        "programs": ledger.programs()}
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
