"""Flash-attention long-context micro-bench (VERDICT r3 weak #2).

Measures the Pallas flash kernel's fwd and fwd+bwd throughput at long
sequence lengths (attention is ~87% of step FLOPs at 128k on the 470m
flagship, so kernel efficiency ~= long-ctx MFU), and sweeps block sizes.

Chained fori_loop timing (CLAUDE.md method): `block_until_ready` does NOT
reliably block through the axon tunnel — single-call sync timings read as
microseconds. Chaining N calls inside one jit (output feeds input) and
timing the whole program resolves per-call cost.

Usage: python benchmarks/flash_longctx.py [S ...] (default 32768 65536)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    seqs = [int(a) for a in sys.argv[1:] if a.isdigit()] or [32768, 65536]
    blocks = [(512, 512), (1024, 1024), (1024, 512), (512, 1024)]
    h, d = 8, 128
    peak = 197e12
    key = jax.random.PRNGKey(0)

    for s in seqs:
        n_iter = max(2, min(16, (32768 * 4) // s))
        q = jax.random.normal(key, (1, s, h, d), jnp.bfloat16)
        k = jax.random.normal(key, (1, s, h, d), jnp.bfloat16)
        v = jax.random.normal(key, (1, s, h, d), jnp.bfloat16)
        fwd_flops = 4 * s * s / 2 * h * d  # causal

        for bq, bk in blocks:
            row = {"seq": s, "block": f"{bq}x{bk}", "iters": n_iter}
            try:
                @jax.jit
                def fwd_chain(q0):
                    def body(i, qc):
                        o = flash_attention(qc, k, v, causal=True,
                                            block_q=bq, block_k=bk)
                        return (o * 1e-3).astype(qc.dtype)
                    return jax.lax.fori_loop(0, n_iter, body, q0)

                float(fwd_chain(q).astype(jnp.float32).sum())  # compile+sync
                t0 = time.perf_counter()
                float(fwd_chain(q).astype(jnp.float32).sum())
                dt = (time.perf_counter() - t0) / n_iter
                row["fwd_ms"] = round(1e3 * dt, 1)
                row["fwd_mfu"] = round(fwd_flops / dt / peak, 3)

                @jax.jit
                def bwd_chain(q0):
                    def body(i, qc):
                        def loss(qq):
                            return flash_attention(
                                qq, k, v, causal=True, block_q=bq,
                                block_k=bk).astype(jnp.float32).sum()
                        g = jax.grad(loss)(qc)
                        return (g * 1e-3).astype(qc.dtype)
                    return jax.lax.fori_loop(0, n_iter, body, q0)

                float(bwd_chain(q).astype(jnp.float32).sum())
                t0 = time.perf_counter()
                float(bwd_chain(q).astype(jnp.float32).sum())
                dt = (time.perf_counter() - t0) / n_iter
                row["fwdbwd_ms"] = round(1e3 * dt, 1)
                # fwd recompute inside grad: fwd + dq + dkv = 3.5x fwd volume
                row["fwdbwd_mfu"] = round(3.5 * fwd_flops / dt / peak, 3)
            except Exception as e:  # OOM etc.
                row["error"] = str(e)[:120]
            print(json.dumps(row))


if __name__ == "__main__":
    main()
